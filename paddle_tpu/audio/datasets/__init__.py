"""Audio datasets (reference: python/paddle/audio/datasets/{tess,esc50}.py).

Zero-egress build: the download step is gated — point ``data_dir`` at a
local copy laid out like the published archive and everything works; with
no local data a clear error explains how to provide it.
"""
from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ...io import Dataset

__all__ = ["TESS", "ESC50"]


class AudioClassificationDataset(Dataset):
    """Common base (reference datasets/dataset.py): wav files + labels,
    feature_type raw/spectrogram/melspectrogram/logmelspectrogram/mfcc."""

    _feat_layers = {
        "raw": None,
        "spectrogram": "Spectrogram",
        "melspectrogram": "MelSpectrogram",
        "logmelspectrogram": "LogMelSpectrogram",
        "mfcc": "MFCC",
    }

    def __init__(self, files: List[str], labels: List[int],
                 feature_type: str = "raw", sample_rate: int = 22050,
                 **kwargs):
        if feature_type not in self._feat_layers:
            raise ValueError(
                f"unknown feature_type {feature_type!r}; choose from "
                f"{sorted(self._feat_layers)}")
        self.files = files
        self.labels = labels
        self.feature_type = feature_type
        self.sample_rate = sample_rate
        if feature_type == "raw":
            self._feat = None
        else:
            from .. import features

            cls = getattr(features, self._feat_layers[feature_type])
            self._feat = cls(sr=sample_rate, **kwargs) \
                if feature_type != "spectrogram" else cls(**kwargs)

    def __getitem__(self, idx):
        from ..backends import load

        wav, _sr = load(self.files[idx])
        x = wav.numpy()
        x = np.asarray(x)[0] if x.ndim == 2 else np.asarray(x)
        if self._feat is not None:
            x = np.asarray(self._feat(x[None]).numpy())[0]
        return x, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.files)


def _require_dir(data_dir: Optional[str], name: str, url_hint: str) -> str:
    if data_dir and os.path.isdir(data_dir):
        return data_dir
    raise RuntimeError(
        f"{name}: no local data. This build has no network egress; download "
        f"the archive ({url_hint}) on a connected machine, extract it, and "
        f"pass data_dir=<path>.")


class TESS(AudioClassificationDataset):
    """Toronto Emotional Speech Set (reference datasets/tess.py). Layout:
    ``<data_dir>/**/<speaker>_<word>_<emotion>.wav``."""

    emotions = ["angry", "disgust", "fear", "happy", "neutral", "ps", "sad"]

    def __init__(self, mode: str = "train", n_folds: int = 5,
                 split: int = 1, feature_type: str = "raw",
                 data_dir: Optional[str] = None, **kwargs):
        root = _require_dir(data_dir, "TESS",
                            "https://doi.org/10.5683/SP2/E8H2MF")
        files, labels = [], []
        for dirpath, _, names in os.walk(root):
            for fn in sorted(names):
                if not fn.lower().endswith(".wav"):
                    continue
                emotion = fn.rsplit("_", 1)[-1][:-4].lower()
                if emotion in self.emotions:
                    files.append(os.path.join(dirpath, fn))
                    labels.append(self.emotions.index(emotion))
        files, labels = self._split(files, labels, mode, n_folds, split)
        super().__init__(files, labels, feature_type, **kwargs)

    @staticmethod
    def _split(files, labels, mode, n_folds, split):
        rng = np.random.RandomState(0)
        order = rng.permutation(len(files))
        folds = [int(i * n_folds / len(files)) + 1 for i in range(len(files))]
        keep = [(f, l) for i, (f, l) in enumerate(
            zip([files[o] for o in order], [labels[o] for o in order]))
            if (folds[i] != split) == (mode == "train")]
        return [f for f, _ in keep], [l for _, l in keep]


class ESC50(AudioClassificationDataset):
    """ESC-50 environmental sounds (reference datasets/esc50.py). Layout:
    ``<data_dir>/audio/*.wav`` named ``<fold>-<src>-<take>-<target>.wav``."""

    def __init__(self, mode: str = "train", split: int = 1,
                 feature_type: str = "raw", data_dir: Optional[str] = None,
                 **kwargs):
        root = _require_dir(data_dir, "ESC50",
                            "https://github.com/karolpiczak/ESC-50")
        audio_dir = os.path.join(root, "audio")
        if not os.path.isdir(audio_dir):
            audio_dir = root
        files, labels = [], []
        for fn in sorted(os.listdir(audio_dir)):
            if not fn.endswith(".wav"):
                continue
            parts = fn[:-4].split("-")
            fold, target = int(parts[0]), int(parts[-1])
            if (fold != split) == (mode == "train"):
                files.append(os.path.join(audio_dir, fn))
                labels.append(target)
        super().__init__(files, labels, feature_type, **kwargs)
