"""Window functions (reference: python/paddle/audio/functional/window.py,
scipy-derived formulas — the formulas are public specs).

All windows return a jnp array; ``get_window`` is the registry entry point.
"""
from __future__ import annotations

import math
from typing import Union

import jax.numpy as jnp

__all__ = ["get_window"]

_REGISTER = {}


def _window(func):
    _REGISTER[func.__name__.lstrip("_")] = func
    return func


def _len_guards(M: int) -> bool:
    if int(M) != M or M < 0:
        raise ValueError("Window length M must be a non-negative integer")
    return M <= 1


def _extend(M: int, sym: bool):
    return (M, False) if sym else (M + 1, True)


def _truncate(w, needed_trunc: bool):
    return w[:-1] if needed_trunc else w


def _general_cosine(M, a, sym=True, dtype="float64"):
    if _len_guards(M):
        return jnp.ones(M, dtype)
    M, trunc = _extend(M, sym)
    fac = jnp.linspace(-math.pi, math.pi, M, dtype=dtype)
    w = jnp.zeros(M, dtype)
    for k, coef in enumerate(a):
        w = w + coef * jnp.cos(k * fac)
    return _truncate(w, trunc)


def _general_hamming(M, alpha, sym=True, dtype="float64"):
    return _general_cosine(M, [alpha, 1.0 - alpha], sym, dtype)


@_window
def _hamming(M, sym=True, dtype="float64"):
    return _general_hamming(M, 0.54, sym, dtype)


@_window
def _hann(M, sym=True, dtype="float64"):
    return _general_hamming(M, 0.5, sym, dtype)


@_window
def _blackman(M, sym=True, dtype="float64"):
    return _general_cosine(M, [0.42, 0.50, 0.08], sym, dtype)


@_window
def _bohman(M, sym=True, dtype="float64"):
    if _len_guards(M):
        return jnp.ones(M, dtype)
    M, trunc = _extend(M, sym)
    fac = jnp.abs(jnp.linspace(-1, 1, M, dtype=dtype)[1:-1])
    w = (1 - fac) * jnp.cos(math.pi * fac) + \
        1.0 / math.pi * jnp.sin(math.pi * fac)
    w = jnp.concatenate([jnp.zeros(1, dtype), w, jnp.zeros(1, dtype)])
    return _truncate(w, trunc)


@_window
def _cosine(M, sym=True, dtype="float64"):
    if _len_guards(M):
        return jnp.ones(M, dtype)
    M, trunc = _extend(M, sym)
    w = jnp.sin(math.pi / M * (jnp.arange(M, dtype=dtype) + 0.5))
    return _truncate(w, trunc)


@_window
def _triang(M, sym=True, dtype="float64"):
    if _len_guards(M):
        return jnp.ones(M, dtype)
    M, trunc = _extend(M, sym)
    n = jnp.arange(1, (M + 1) // 2 + 1, dtype=dtype)
    if M % 2 == 0:
        w = (2 * n - 1.0) / M
        w = jnp.concatenate([w, w[::-1]])
    else:
        w = 2 * n / (M + 1.0)
        w = jnp.concatenate([w, w[-2::-1]])
    return _truncate(w, trunc)


@_window
def _gaussian(M, std=7, sym=True, dtype="float64"):
    if _len_guards(M):
        return jnp.ones(M, dtype)
    M, trunc = _extend(M, sym)
    n = jnp.arange(0, M, dtype=dtype) - (M - 1.0) / 2.0
    w = jnp.exp(-(n ** 2) / (2 * std * std))
    return _truncate(w, trunc)


@_window
def _exponential(M, center=None, tau=1.0, sym=True, dtype="float64"):
    if sym and center is not None:
        raise ValueError("If sym==True, center must be None.")
    if _len_guards(M):
        return jnp.ones(M, dtype)
    M, trunc = _extend(M, sym)
    if center is None:
        center = (M - 1) / 2
    n = jnp.arange(0, M, dtype=dtype)
    w = jnp.exp(-jnp.abs(n - center) / tau)
    return _truncate(w, trunc)


@_window
def _tukey(M, alpha=0.5, sym=True, dtype="float64"):
    if _len_guards(M):
        return jnp.ones(M, dtype)
    if alpha <= 0:
        return jnp.ones(M, dtype)
    if alpha >= 1.0:
        return _hann(M, sym=sym, dtype=dtype)
    M, trunc = _extend(M, sym)
    n = jnp.arange(0, M, dtype=dtype)
    width = int(alpha * (M - 1) / 2.0)
    n1 = n[0:width + 1]
    n2 = n[width + 1:M - width - 1]
    n3 = n[M - width - 1:]
    w1 = 0.5 * (1 + jnp.cos(math.pi * (-1 + 2.0 * n1 / alpha / (M - 1))))
    w2 = jnp.ones(n2.shape[0], dtype)
    w3 = 0.5 * (1 + jnp.cos(math.pi * (-2.0 / alpha + 1 +
                                       2.0 * n3 / alpha / (M - 1))))
    return _truncate(jnp.concatenate([w1, w2, w3]), trunc)


@_window
def _taylor(M, nbar=4, sll=30, norm=True, sym=True, dtype="float64"):
    if _len_guards(M):
        return jnp.ones(M, dtype)
    M, trunc = _extend(M, sym)
    B = 10 ** (sll / 20)
    A = float(jnp.arccosh(jnp.asarray(B, jnp.float64))) / math.pi
    s2 = nbar ** 2 / (A ** 2 + (nbar - 0.5) ** 2)
    ma = jnp.arange(1, nbar, dtype=dtype)
    Fm = []
    signs = jnp.empty_like(ma)
    signs = signs.at[::2].set(-1)
    signs = signs.at[1::2].set(1)
    m2 = ma * ma
    for mi in range(len(ma)):
        numer = signs[mi] * jnp.prod(
            1 - m2[mi] / s2 / (A ** 2 + (ma - 0.5) ** 2))
        denom = 2 * jnp.prod(1 - m2[mi] / m2[:mi]) * jnp.prod(
            1 - m2[mi] / m2[mi + 1:])
        Fm.append(numer / denom)
    Fm = jnp.stack(Fm)

    def W(n):
        return 1 + 2 * jnp.dot(
            Fm, jnp.cos(2 * math.pi * ma[:, None]
                        * (n - M / 2.0 + 0.5) / M))

    w = W(jnp.arange(0, M, dtype=dtype))
    if norm:
        w = w / W((M - 1) / 2)
    return _truncate(w.astype(dtype), trunc)


def get_window(window: Union[str, tuple], win_length: int,
               fftbins: bool = True, dtype: str = "float64"):
    """Window by name or (name, param) tuple (reference get_window:327)."""
    sym = not fftbins
    if isinstance(window, tuple):
        winstr = window[0]
        args = window[1:]
    elif isinstance(window, str):
        winstr = window
        args = ()
    else:
        raise ValueError(f"The window type {type(window)} is not supported")
    try:
        winfunc = _REGISTER[winstr]
    except KeyError as e:
        raise ValueError(f"Unknown window type: {winstr}") from e
    return winfunc(win_length, *args, sym=sym, dtype=dtype)
