"""Audio DSP functional surface (reference:
python/paddle/audio/functional/functional.py — librosa-style formulas).
"""
from __future__ import annotations

import math
from typing import Optional, Union

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._helpers import unwrap

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct"]


def hz_to_mel(freq, htk: bool = False):
    """Hz → mel (reference functional.py:29; Slaney by default)."""
    scalar = not isinstance(freq, (Tensor, jnp.ndarray))
    f = jnp.asarray(unwrap(freq), jnp.float32) if not scalar else float(freq)
    if htk:
        out = 2595.0 * (jnp.log10(1.0 + f / 700.0) if not scalar
                        else math.log10(1.0 + f / 700.0))
        return float(out) if scalar else Tensor(out)
    f_min, f_sp = 0.0, 200.0 / 3
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    if scalar:
        mels = (f - f_min) / f_sp
        if f >= min_log_hz:
            mels = min_log_mel + math.log(f / min_log_hz) / logstep
        return mels
    mels = (f - f_min) / f_sp
    mels = jnp.where(f >= min_log_hz,
                     min_log_mel + jnp.log(jnp.maximum(f, 1e-10)
                                           / min_log_hz) / logstep, mels)
    return Tensor(mels)


def mel_to_hz(mel, htk: bool = False):
    """mel → Hz (reference functional.py:77)."""
    scalar = not isinstance(mel, (Tensor, jnp.ndarray))
    m = jnp.asarray(unwrap(mel), jnp.float32) if not scalar else float(mel)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
        return out if scalar else Tensor(out)
    f_min, f_sp = 0.0, 200.0 / 3
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    if scalar:
        if m >= min_log_mel:
            return min_log_hz * math.exp(logstep * (m - min_log_mel))
        return f_min + f_sp * m
    freqs = f_min + f_sp * m
    freqs = jnp.where(m >= min_log_mel,
                      min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                      freqs)
    return Tensor(freqs)


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False,
                    dtype: str = "float32"):
    """n_mels mel-spaced frequencies (reference functional.py:117)."""
    min_mel = hz_to_mel(f_min, htk=htk)
    max_mel = hz_to_mel(f_max, htk=htk)
    mels = jnp.linspace(min_mel, max_mel, n_mels, dtype=dtype)
    return Tensor(unwrap(mel_to_hz(mels, htk=htk)).astype(dtype))


def fft_frequencies(sr: int, n_fft: int, dtype: str = "float32"):
    """FFT bin center frequencies (reference functional.py:145)."""
    return Tensor(jnp.linspace(0, sr / 2.0, 1 + n_fft // 2, dtype=dtype))


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm: Union[str, float] = "slaney",
                         dtype: str = "float32"):
    """Mel filterbank [n_mels, 1 + n_fft//2] (reference functional.py:163)."""
    if f_max is None:
        f_max = float(sr) / 2
    fftfreqs = unwrap(fft_frequencies(sr, n_fft, dtype))
    mel_f = unwrap(mel_frequencies(n_mels + 2, f_min, f_max, htk, dtype))
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]        # [n_mels+2, bins]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    elif isinstance(norm, (int, float)):
        norms = jnp.linalg.norm(weights, ord=norm, axis=-1, keepdims=True)
        weights = weights / jnp.maximum(norms, 1e-10)
    return Tensor(weights.astype(dtype))


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    """Power → dB with clamping (reference functional.py:232)."""
    if amin <= 0:
        raise ValueError("amin must be strictly positive")
    if ref_value <= 0:
        raise ValueError("ref_value must be strictly positive")
    x = jnp.asarray(unwrap(spect))
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, x))
    log_spec = log_spec - 10.0 * math.log10(max(ref_value, amin))
    if top_db is not None:
        if top_db < 0:
            raise ValueError("top_db must be non-negative")
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return Tensor(log_spec)


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho",
               dtype: str = "float32"):
    """DCT-II matrix [n_mels, n_mfcc] (reference functional.py:286)."""
    n = jnp.arange(float(n_mels))
    k = jnp.arange(float(n_mfcc))[:, None]
    dct = jnp.cos(math.pi / float(n_mels) * (n + 0.5) * k)
    if norm is None:
        dct = dct * 2.0
    else:
        if norm != "ortho":
            raise ValueError(f"norm must be 'ortho' or None, got {norm}")
        dct = dct.at[0].multiply(1.0 / math.sqrt(2.0))
        dct = dct * math.sqrt(2.0 / float(n_mels))
    return Tensor(dct.T.astype(dtype))
