"""Dy2static control-flow conversion (reference: jit/dy2static/ —
program_translator.py:305 + ifelse_transformer.py / loop_transformer.py /
logical_transformer.py among its ~20 AST transformers).

The reference rewrites imperative Python into ProgramDesc control-flow ops
(cond / while). The TPU-native target is XLA: tensor-predicate ``if`` /
``while`` must become ``lax.cond`` / ``lax.while_loop`` or jit tracing
either fails or silently specializes on the traced branch. This module is
the same architecture at 1/30 the code because JAX traces natively and only
CONTROL FLOW needs source rewriting:

- :func:`convert_to_static` parses the function source, rewrites

  * ``if <pred>: A else: B``      -> ``convert_ifelse(pred, tfn, ffn, vars)``
  * ``while <pred>: BODY``        -> ``convert_while(cond_fn, body_fn, vars)``
  * ``a and b`` / ``a or b``      -> lazy ``convert_logical_and/or``
  * ``not a``                     -> ``convert_logical_not``

  using autograph-style nested functions whose arguments/returns are the
  branch-assigned variables (no nonlocal mutation under trace).
- The runtime converters dispatch on the predicate: a concrete Python/numpy
  bool keeps plain Python semantics (zero overhead, branches may diverge in
  structure); a traced value lowers to ``lax.cond``/``lax.while_loop``.
- Patterns that cannot lower (``break``/``continue``/``return`` inside a
  tensor-predicate loop) are left as Python and surface as a LOUD error
  naming the function and the rewrite (:func:`control_flow_guidance`).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["convert_to_static", "convert_ifelse", "convert_while",
           "convert_logical_and", "convert_logical_or",
           "convert_logical_not", "control_flow_guidance"]


# --------------------------------------------------------------------------
# runtime converters
# --------------------------------------------------------------------------

def _raw(x):
    return x._value if isinstance(x, Tensor) else x


def _is_dynamic(pred) -> bool:
    """True when the predicate is a traced value (jit trace time) — the
    only case that must lower to lax control flow."""
    return isinstance(_raw(pred), jax.core.Tracer)


def convert_ifelse(pred, true_fn: Callable, false_fn: Callable,
                   args: Tuple = ()):
    """``if``/``else`` with branch-assigned vars passed through ``args``
    and returned as a tuple. Traced predicate -> ``lax.cond`` (both
    branches traced, structures must match); concrete -> plain call."""
    if not _is_dynamic(pred):
        return true_fn(*args) if bool(_raw(pred)) else false_fn(*args)
    from jax import lax

    try:
        return lax.cond(jnp.asarray(_raw(pred)).astype(bool).reshape(()),
                        true_fn, false_fn, *args)
    except TypeError as e:
        raise TypeError(
            f"to_static: the two branches of a tensor-predicate `if` must "
            f"produce matching shapes/dtypes for every assigned variable "
            f"(lax.cond contract). {control_flow_guidance()}") from e


class Undefined:
    """A local that no branch/loop iteration has assigned yet (autograph's
    'Undefined' pattern): VALUE-like use fails loudly with the variable
    name, while attribute probes stay inert (hasattr checks from pytree
    flattening must see a plain AttributeError, not a crash)."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def _die(self, *a, **k):
        raise UnboundLocalError(
            f"local variable {self.name!r} referenced before assignment "
            f"(a dy2static-converted branch/loop did not bind it on the "
            f"path taken)")

    def __repr__(self):
        return f"<undefined local {self.name!r}>"

    __bool__ = __iter__ = __len__ = __call__ = _die
    __add__ = __radd__ = __sub__ = __rsub__ = _die
    __mul__ = __rmul__ = __truediv__ = __rtruediv__ = _die
    __neg__ = __float__ = __int__ = __getitem__ = _die
    __lt__ = __le__ = __gt__ = __ge__ = _die
    __eq__ = __ne__ = _die          # v == 2 must not silently be False
    __hash__ = object.__hash__      # (defining __eq__ clears __hash__)


def convert_while(cond_fn: Callable, body_fn: Callable, init: Tuple):
    """``while`` with loop-carried vars. Concrete condition -> plain Python
    iteration (checked once per iteration; may go dynamic mid-loop, in
    which case lax takes over FROM THE CURRENT state); traced condition ->
    ``lax.while_loop`` (body must keep shapes/dtypes)."""
    vars_ = tuple(init)
    c = cond_fn(*vars_)
    while not _is_dynamic(c):
        if not bool(_raw(c)):
            return vars_
        vars_ = tuple(body_fn(*vars_))
        c = cond_fn(*vars_)
    from jax import lax

    try:
        return lax.while_loop(
            lambda vs: jnp.asarray(
                _raw(cond_fn(*vs))).astype(bool).reshape(()),
            lambda vs: tuple(body_fn(*vs)), vars_)
    except TypeError as e:
        raise TypeError(
            f"to_static: a tensor-predicate `while` body must keep every "
            f"loop variable's shape and dtype fixed (lax.while_loop "
            f"contract). {control_flow_guidance()}") from e


def convert_logical_and(lhs_fn: Callable, rhs_fn: Callable):
    l = lhs_fn()
    if not _is_dynamic(l):
        return l if not bool(_raw(l)) else rhs_fn()
    return jnp.logical_and(jnp.asarray(_raw(l)).astype(bool),
                           jnp.asarray(_raw(rhs_fn())).astype(bool))


def convert_logical_or(lhs_fn: Callable, rhs_fn: Callable):
    l = lhs_fn()
    if not _is_dynamic(l):
        return l if bool(_raw(l)) else rhs_fn()
    return jnp.logical_or(jnp.asarray(_raw(l)).astype(bool),
                          jnp.asarray(_raw(rhs_fn())).astype(bool))


def convert_logical_not(x):
    if not _is_dynamic(x):
        return not bool(_raw(x))
    return jnp.logical_not(jnp.asarray(_raw(x)).astype(bool))


def control_flow_guidance() -> str:
    return (
        "Supported rewrites: (1) keep the `if`/`while` free of "
        "break/continue/return so dy2static can lower it to "
        "lax.cond/lax.while_loop; (2) use jnp.where / paddle.where for "
        "per-element selection; (3) hoist the data-dependent decision out "
        "of the jitted function; (4) mark the function @not_to_static to "
        "run it eagerly.")


# --------------------------------------------------------------------------
# AST transformation
# --------------------------------------------------------------------------

_RT = "_paddle_jst"          # runtime module alias injected into globals


class _AssignedNames(ast.NodeVisitor):
    """Names bound by a statement list (stopping at nested scopes)."""

    def __init__(self):
        self.names: List[str] = []

    def _add(self, name):
        if name not in self.names:
            self.names.append(name)

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self._add(node.id)

    def visit_FunctionDef(self, node):
        self._add(node.name)      # binds the name; don't descend

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def visit_ListComp(self, node):  # comprehensions have their own scope
        pass

    visit_SetComp = visit_DictComp = visit_GeneratorExp = visit_ListComp


def _assigned(stmts) -> List[str]:
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _HasEscape(ast.NodeVisitor):
    """break/continue/return/yield at this control-flow level (not inside a
    nested loop for break/continue, never inside a nested function)."""

    def __init__(self):
        self.found = False

    def visit_Return(self, node):
        self.found = True

    def visit_Yield(self, node):
        self.found = True

    visit_YieldFrom = visit_Yield

    def visit_Break(self, node):
        self.found = True

    def visit_Continue(self, node):
        self.found = True

    def visit_While(self, node):      # nested loop owns its break/continue
        for s in node.body + node.orelse:
            _ret = _ReturnOnly()
            _ret.visit(s)
            self.found = self.found or _ret.found

    visit_For = visit_While

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_Lambda = visit_FunctionDef


class _ReturnOnly(ast.NodeVisitor):
    def __init__(self):
        self.found = False

    def visit_Return(self, node):
        self.found = True

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_Lambda = visit_FunctionDef


def _has_escape(stmts) -> bool:
    v = _HasEscape()
    for s in stmts:
        v.visit(s)
    return v.found


def _uses_global_nonlocal(stmts) -> bool:
    for s in stmts:
        for n in ast.walk(s):
            if isinstance(n, (ast.Global, ast.Nonlocal)):
                return True
    return False


class _ControlFlowTransformer(ast.NodeTransformer):
    """Statement-level rewrite with a sequential maybe-bound name table (a
    name assigned anywhere earlier in document order counts as bound — the
    autograph approximation; truly-unbound names fail at the call site the
    same way they would have in the original code)."""

    def __init__(self):
        self._uid = 0
        self.bound: List[str] = []
        self.changed = False

    def _fresh(self, kind):
        self._uid += 1
        return f"__pt_{kind}_{self._uid}"

    def _bind(self, names):
        for n in names:
            if n not in self.bound:
                self.bound.append(n)

    # -- scope roots -------------------------------------------------------
    def visit_FunctionDef(self, node, _outer=True):
        args = node.args
        names = [a.arg for a in (args.posonlyargs + args.args
                                 + args.kwonlyargs)]
        if args.vararg:
            names.append(args.vararg.arg)
        if args.kwarg:
            names.append(args.kwarg.arg)
        self._bind(names)
        node.body = self._visit_block(node.body)
        return node

    def _visit_block(self, stmts):
        out = []
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                # nested scopes are left untouched: transforming them with
                # the OUTER maybe-bound table could turn a valid closure
                # read into an unbound argument
                self._bind([s.name])
                out.append(s)
                continue
            r = self.visit(s)
            self._bind(_assigned([s]))
            if isinstance(r, list):
                out.extend(r)
            elif r is not None:
                out.append(r)
        return out

    # -- expression rewrites ----------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        expr = node.values[-1]
        for prev in reversed(node.values[:-1]):
            expr = ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id=_RT, ctx=ast.Load()), attr=fn,
                    ctx=ast.Load()),
                args=[ast.Lambda(args=_empty_args(), body=prev),
                      ast.Lambda(args=_empty_args(), body=expr)],
                keywords=[])
        self.changed = True
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            self.changed = True
            return ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id=_RT, ctx=ast.Load()),
                    attr="convert_logical_not", ctx=ast.Load()),
                args=[node.operand], keywords=[])
        return node

    # -- statement rewrites -----------------------------------------------
    def visit_If(self, node):
        node.test = self.visit(node.test)
        if (_has_escape(node.body) or _has_escape(node.orelse)
                or _uses_global_nonlocal(node.body + node.orelse)):
            # unconvertible: leave as Python (concrete predicates still
            # work; traced ones get the loud guidance error from jit)
            node.body = self._visit_block(node.body)
            node.orelse = self._visit_block(node.orelse)
            return node
        bound_before = list(self.bound)   # snapshot: names live BEFORE the
        body = self._visit_block(list(node.body))     # if, not branch-born
        orelse = self._visit_block(list(node.orelse))
        outs = _assigned(node.body + node.orelse)
        passed = [n for n in outs if n in bound_before]
        born = [n for n in outs if n not in bound_before]
        # branch-born names start as Undefined INSIDE each branch fn (never
        # as lax.cond operands): a branch that assigns returns the value, a
        # branch that doesn't returns the placeholder — concrete paths keep
        # Python semantics, traced asymmetry fails the cond structure check
        tname, fname = self._fresh("true"), self._fresh("false")
        tdef = _make_branch_fn(tname, passed,
                               [_undef_assign(n) for n in born] + body, outs)
        fdef = _make_branch_fn(fname, passed,
                               [_undef_assign(n) for n in born] + orelse,
                               outs)
        call = ast.Call(
            func=ast.Attribute(value=ast.Name(id=_RT, ctx=ast.Load()),
                               attr="convert_ifelse", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=tname, ctx=ast.Load()),
                  ast.Name(id=fname, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Load())
                                  for n in passed], ctx=ast.Load())],
            keywords=[])
        assign = _tuple_assign(outs, call)
        self._bind(outs)
        self.changed = True
        return [tdef, fdef, assign]

    def visit_While(self, node):
        node.test = self.visit(node.test)
        if (node.orelse or _has_escape(node.body)
                or _uses_global_nonlocal(node.body)):
            node.body = self._visit_block(node.body)
            node.orelse = self._visit_block(node.orelse)
            return node
        bound_before = list(self.bound)
        body = self._visit_block(list(node.body))
        carried = _assigned(node.body)
        if not carried:
            # nothing assigned: a tensor predicate would never progress;
            # leave as Python (concrete predicates work unchanged)
            node.body = body
            return node
        # loop-born names (first assigned in the body) start as Undefined
        # placeholders so they are carried and visible after the loop —
        # matching Python, where they exist iff an iteration ran
        pre = [_undef_assign(n) for n in carried if n not in bound_before]
        cname, bname = self._fresh("cond"), self._fresh("body")
        cdef = _make_branch_fn(cname, carried, [], [], ret_expr=node.test)
        bdef = _make_branch_fn(bname, carried, body, carried)
        call = ast.Call(
            func=ast.Attribute(value=ast.Name(id=_RT, ctx=ast.Load()),
                               attr="convert_while", ctx=ast.Load()),
            args=[ast.Name(id=cname, ctx=ast.Load()),
                  ast.Name(id=bname, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Load())
                                  for n in carried], ctx=ast.Load())],
            keywords=[])
        assign = _tuple_assign(carried, call)
        self._bind(carried)
        self.changed = True
        return pre + [cdef, bdef, assign]

    def visit_FunctionDef_nested(self, node):
        return node


def _undef_assign(name: str):
    """``name = _RT.Undefined('name')`` — placeholder for a branch/loop-
    born local."""
    return ast.Assign(
        targets=[ast.Name(id=name, ctx=ast.Store())],
        value=ast.Call(
            func=ast.Attribute(value=ast.Name(id=_RT, ctx=ast.Load()),
                               attr="Undefined", ctx=ast.Load()),
            args=[ast.Constant(value=name)], keywords=[]))


def _empty_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


def _make_branch_fn(name: str, params: List[str], body: List[ast.stmt],
                    outs: List[str], ret_expr: Optional[ast.expr] = None):
    """def name(p1, ..., pN): BODY; return (o1, ..., oM)"""
    ret_val = (ret_expr if ret_expr is not None else
               ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Load())
                               for n in outs], ctx=ast.Load()))
    fn_body = list(body) + [ast.Return(value=ret_val)]
    args = ast.arguments(
        posonlyargs=[],
        args=[ast.arg(arg=p) for p in params],
        vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])
    return ast.FunctionDef(name=name, args=args, body=fn_body,
                           decorator_list=[], returns=None,
                           type_params=[])


def _tuple_assign(names: List[str], value: ast.expr):
    # always a tuple target — the converters return tuples even for one var
    tgt = ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Store())
                          for n in names], ctx=ast.Store())
    return ast.Assign(targets=[tgt], value=value)


# --------------------------------------------------------------------------
# entry point
# --------------------------------------------------------------------------

def convert_to_static(fn: Callable) -> Callable:
    """Source-rewrite ``fn``'s control flow for jit tracing. Returns the
    transformed function, or ``fn`` unchanged when transformation is not
    possible (no source, closures, parse failure) — tracing then relies on
    the loud-error path for tensor predicates."""
    if getattr(fn, "_not_to_static", False):
        return fn
    inner = getattr(fn, "__func__", fn)       # unwrap bound methods
    if getattr(inner, "__closure__", None):
        return fn                             # cells can't be rebuilt
    try:
        src = textwrap.dedent(inspect.getsource(inner))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []                  # strip @to_static etc.
    tr = _ControlFlowTransformer()
    try:
        tr.visit_FunctionDef(fdef)
    except Exception:
        return fn
    if not tr.changed:
        return fn
    ast.fix_missing_locations(tree)
    import linecache
    import sys

    ns: Dict[str, Any] = dict(inner.__globals__)
    ns[_RT] = sys.modules[__name__]
    filename = f"<dy2static {inner.__name__}>"
    try:
        new_src = ast.unparse(tree)
        code = compile(tree, filename=filename, mode="exec")
        exec(code, ns)
    except Exception:
        return fn
    new_fn = ns[fdef.name]
    functools.update_wrapper(new_fn, inner)
    new_fn.__wrapped_original__ = fn
    new_fn.__dy2static_source__ = new_src
    # tracebacks and inspect.getsource resolve through linecache
    linecache.cache[filename] = (
        len(new_src), None, [l + "\n" for l in new_src.splitlines()],
        filename)
    if hasattr(fn, "__self__"):               # rebind methods
        import types

        return types.MethodType(new_fn, fn.__self__)
    return new_fn
