"""paddle.jit parity (python/paddle/jit/api.py:233 to_static).

TPU-native redesign: the reference's dy2static subsystem (15K LoC of AST
transformers, jit/dy2static/) exists because imperative Python had to become
a ProgramDesc graph. Under JAX, tracing IS native — ``to_static`` wraps the
layer/function into a pure function of (params, buffers, rng_key, inputs) and
compiles it with ``jax.jit``. Autograd still works: the compiled forward is
recorded on the eager tape via ``jax.vjp`` over the jitted callable, so
``loss.backward()`` runs a compiled backward as well.

Buffer mutation (BatchNorm running stats) is functionalized: buffers are
threaded out of the pure function and written back after each call.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.tree_util import tree_flatten, tree_unflatten

from ..core.autograd import GradNode, is_grad_enabled, no_grad
from ..core.random import default_generator, trace_key_scope
from ..core.tensor import Tensor

__all__ = ["to_static", "not_to_static", "enable_to_static", "TracedLayer",
           "save", "load"]

_to_static_enabled = [True]


def enable_to_static(flag: bool):
    _to_static_enabled[0] = bool(flag)


def _is_tensor(x):
    return isinstance(x, Tensor)


class StaticFunction:
    """≙ reference StaticFunction (jit/dy2static/program_translator.py:305)."""

    def __init__(self, function: Callable, layer=None, input_spec=None):
        from .dy2static import convert_to_static

        # dy2static pass: tensor-predicate if/while become lax.cond /
        # lax.while_loop (reference program_translator.py:305 + the
        # *_transformer.py set); falls back to the untransformed function
        # when the source can't be rewritten
        self._fn = convert_to_static(function)
        self._raw_fn = function
        self._layer = layer
        self._input_spec = input_spec
        self._jit_cache: Dict[Any, Callable] = {}
        functools.update_wrapper(self, function,
                                 assigned=("__name__", "__doc__", "__qualname__"),
                                 updated=())

    # -- helpers -----------------------------------------------------------
    def _state(self):
        if self._layer is None:
            return {}, {}, []
        params = dict(self._layer.named_parameters())
        buffers = dict(self._layer.named_buffers())
        return params, buffers, list(buffers.keys())

    def _make_pure(self, treedef, n_tensors, const_leaves, training, meta):
        fn = self._fn
        layer = self._layer

        def pure(pvals, bvals, key, tvals):
            params, buffers, _ = self._state()
            old_p = {k: p._value for k, p in params.items()}
            old_b = {k: b._value for k, b in buffers.items()}
            old_nodes = {k: p._node for k, p in params.items()}
            try:
                for k, p in params.items():
                    p._value = pvals[k]
                    p._node = None
                for k, b in buffers.items():
                    b._value = bvals[k]
                leaves = list(const_leaves)
                ti = iter(tvals)
                leaves = [next(ti) if l is _TENSOR_SLOT else l for l in leaves]
                args, kwargs = tree_unflatten(treedef, leaves)
                with no_grad(), trace_key_scope(key):
                    out = fn(*args, **kwargs)
                out_leaves, out_treedef = tree_flatten(
                    out, is_leaf=_is_tensor)
                out_vals = [o._value if isinstance(o, Tensor) else o
                            for o in out_leaves]
                meta["out_treedef"] = out_treedef  # static; set at trace time
                new_b = {k: b._value for k, b in buffers.items()}
                return tuple(out_vals), new_b
            finally:
                for k, p in params.items():
                    p._value = old_p[k]
                    p._node = old_nodes[k]
                for k, b in buffers.items():
                    b._value = old_b[k]

        return pure

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled[0]:
            # true dygraph semantics: the UNtransformed function (the
            # rewritten one would trace both branches of a lax.cond)
            return self._raw_fn(*args, **kwargs)
        params, buffers, buf_keys = self._state()
        leaves, treedef = tree_flatten((args, kwargs), is_leaf=_is_tensor)
        t_idx = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
        tvals = [leaves[i]._value for i in t_idx]
        const_leaves = [_TENSOR_SLOT if isinstance(l, Tensor) else l
                        for l in leaves]
        training = self._layer.training if self._layer is not None else False

        # cache key: structure + training flag + const hash
        ck = (treedef, training, tuple(
            (i, repr(l)) for i, l in enumerate(const_leaves)
            if l is not _TENSOR_SLOT and not isinstance(l, (int, float, bool, str, type(None)))
        ))
        cached = self._jit_cache.get(ck)
        if cached is None:
            meta: Dict[str, Any] = {}
            pure = self._make_pure(treedef, len(t_idx), const_leaves, training, meta)
            from .. import monitor

            # monitored_jit: recompiles of a to_static program show up in
            # paddle_tpu_jit_cache_miss_total{fn=<function name>}
            cached = (monitor.monitored_jit(
                pure,
                name="to_static:" + getattr(self._raw_fn, "__name__",
                                            "fn")), meta)
            self._jit_cache[ck] = cached
        jitted, meta = cached

        key = default_generator.next_key()
        pvals = {k: p._value for k, p in params.items()}
        bvals = {k: b._value for k, b in buffers.items()}

        grad_wanted = is_grad_enabled() and (
            any(not p.stop_gradient for p in params.values())
            or any(not leaves[i].stop_gradient for i in t_idx))

        if not grad_wanted:
            try:
                out_vals, new_b = jitted(pvals, bvals, key, tvals)
            except (jax.errors.ConcretizationTypeError,
                    jax.errors.TracerArrayConversionError,
                    jax.errors.TracerIntegerConversionError) as e:
                self._raise_control_flow(e)
            self._write_buffers(buffers, new_b)
            outs = [Tensor(v, stop_gradient=True) for v in out_vals]
            return tree_unflatten(meta["out_treedef"], outs)

        def diff_fn(pv, tv):
            return jitted(pv, bvals, key, tv)

        try:
            out_vals, vjp_fn, new_b = jax.vjp(diff_fn, pvals, tvals,
                                              has_aux=True)
        except (jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError,
                jax.errors.TracerIntegerConversionError) as e:
            self._raise_control_flow(e)
        self._write_buffers(buffers, new_b)
        out_treedef = meta["out_treedef"]

        param_list = list(params.values())
        input_tensors = [leaves[i] for i in t_idx]

        def node_vjp(cotangents):
            pgrads, tgrads = vjp_fn(tuple(cotangents))
            return [pgrads[k] for k in params.keys()] + list(tgrads)

        out_avals = [(jnp.shape(v), jnp.result_type(v)) for v in out_vals]
        import jax.tree_util as jtu

        node = GradNode(
            node_vjp, param_list + input_tensors,
            jtu.tree_structure(list(range(len(out_vals)))), out_avals,
            name=f"to_static[{getattr(self._fn, '__name__', 'fn')}]")
        outs = []
        for i, v in enumerate(out_vals):
            t = Tensor(v, stop_gradient=False)
            t._node = node
            t._out_idx = i
            outs.append(t)
        return tree_unflatten(out_treedef, outs)

    def _raise_control_flow(self, e):
        """Loud, actionable tracer error (VERDICT r2 #8: never silently
        specialize; name the pattern and the rewrite)."""
        from .dy2static import control_flow_guidance

        raise RuntimeError(
            f"to_static[{getattr(self._raw_fn, '__name__', 'fn')}]: "
            f"data-dependent Python control flow reached the tracer — "
            f"dy2static could not convert this pattern (typically "
            f"break/continue/return inside a tensor-predicate if/while, "
            f"`for` over a tensor-valued range, or a tensor used as a "
            f"plain Python bool outside if/while).\n"
            f"{control_flow_guidance()}\n"
            f"Tracer error: {e}") from e

    @staticmethod
    def _write_buffers(buffers, new_b):
        for k, b in buffers.items():
            nv = new_b.get(k)
            if nv is not None:
                b._value = nv

    @property
    def code(self):
        """Transformed source when dy2static rewrote the function
        (reference StaticFunction.code shows converted code), else the
        original source."""
        import inspect

        src = getattr(getattr(self._fn, "__func__", self._fn),
                      "__dy2static_source__", None)
        if src is not None:
            return src
        return inspect.getsource(self._raw_fn)


class _TensorSlot:
    def __repr__(self):
        return "<tensor>"


_TENSOR_SLOT = _TensorSlot()


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Decorator/wrapper compiling a dygraph function or Layer.forward."""

    def decorate(fn):
        from ..nn.layer.layers import Layer

        if isinstance(fn, Layer):
            layer = fn
            static_fwd = StaticFunction(layer.forward, layer=layer,
                                        input_spec=input_spec)
            layer.forward = static_fwd
            return layer
        # plain function, possibly an unbound method used on a layer
        return StaticFunction(fn, layer=None, input_spec=input_spec)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


class TracedLayer:
    """Minimal trace-and-run artifact (reference paddle.jit.TracedLayer)."""

    def __init__(self, static_fn):
        self._fn = static_fn

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


def save(layer, path, input_spec=None, **configs):
    """jit.save: persist weights + (with input_spec) an AOT artifact.

    The reference emits *.pdmodel (ProgramDesc) + *.pdiparams. TPU-native
    artifact: state_dict pickle (``.pdiparams``) + jax-exported StableHLO
    (``.stablehlo``) when ``input_spec`` gives concrete shapes — the
    serving half loaded by ``paddle_tpu.inference.Predictor``.
    """
    from ..framework.io import save as fsave

    fsave(layer.state_dict(), path + ".pdiparams")
    if input_spec:
        import jax as _jax
        from jax import export as _jexport

        from ..core.tensor import Tensor as _T
        from ..inference.aot import save_exported
        from ..nn.functional_call import functional_call

        params = {k: p.value for k, p in layer.named_parameters()}

        # export fwd(params, *inputs): weights stay in the .pdiparams pickle
        # instead of being baked into the StableHLO as constants (a 350M
        # model would otherwise ship its 700MB twice)
        def fwd(pv, *xs):
            return functional_call(layer, pv, *[_T(x) for x in xs])

        # None/-1 dims become jax.export symbolic dimensions so the artifact
        # serves any batch size, matching the reference InputSpec contract
        shapes = []
        for i, spec in enumerate(input_spec):
            dims = []
            for j, s in enumerate(getattr(spec, "shape", spec)):
                if s is None or (isinstance(s, int) and s < 0):
                    dims.append(f"d{i}_{j}")
                else:
                    dims.append(str(int(s)))
            dt = str(getattr(spec, "dtype", "float32")).replace("paddle.", "")
            shapes.append(_jax.ShapeDtypeStruct(
                _jexport.symbolic_shape(",".join(dims)), dt))
        param_shapes = _jax.tree.map(
            lambda v: _jax.ShapeDtypeStruct(v.shape, v.dtype), params)
        exported = _jexport.export(_jax.jit(fwd))(param_shapes, *shapes)
        save_exported(exported, path + ".stablehlo")


def load(path, **configs):
    from ..framework.io import load as fload

    return fload(path + ".pdiparams")


class TranslatedLayer:
    """Layer-shaped wrapper over a jit.load artifact (reference
    jit/translated_layer.py TranslatedLayer — what jit.load returns for a
    saved static model). jit.load here already returns a callable with
    parameters; this class names the contract and adds program()/train()/
    eval() for API parity."""

    def __init__(self, loaded):
        self._loaded = loaded
        self.training = False

    def __call__(self, *args, **kwargs):
        return self._loaded(*args, **kwargs)

    forward = __call__

    def program(self, method_name: str = "forward"):
        return getattr(self._loaded, "_exported", None)

    def train(self):
        self.training = True
        return self

    def eval(self):
        self.training = False
        return self

    def __getattr__(self, item):
        return getattr(self._loaded, item)


_ignored_modules = set()


def ignore_module(modules):
    """Register modules dy2static must not transcribe (reference
    jit/api.py ignore_module). The JAX tracer never rewrites module
    source, so registration is bookkeeping that not_to_static consults."""
    if not isinstance(modules, (list, tuple, set)):
        modules = [modules]
    _ignored_modules.update(getattr(m, "__name__", str(m)) for m in modules)
    return sorted(_ignored_modules)


_verbosity = [0]
_code_level = [0]


def set_verbosity(level: int = 0, also_to_stdout: bool = False):
    """Dy2static transcription log verbosity (reference jit/dy2static/
    logging_utils.py). The tracer here is jax.jit, so this only gates the
    to_static debug prints."""
    _verbosity[0] = int(level)


def set_code_level(level: int = 100, also_to_stdout: bool = False):
    """Code-dump level for transformed functions (reference analog). With
    jax tracing there is no transformed python source; when >0,
    to_static logs the jaxpr instead."""
    _code_level[0] = int(level)


__all__ += ["TranslatedLayer", "ignore_module", "set_verbosity",
            "set_code_level"]
