"""Model hub (reference: python/paddle/hapi/hub.py — hubconf.py protocol
over a local dir / github / gitee repo).

Zero-egress build: ``source='local'`` is fully supported (import
``hubconf.py`` from the directory, expose its callables); the remote
sources raise a clear error instead of silently failing mid-download.
"""
from __future__ import annotations

import importlib.util
import os
import sys
from typing import List

__all__ = ["list", "help", "load"]

MODULE_HUBCONF = "hubconf.py"
VAR_DEPENDENCY = "dependencies"


def _import_module(name: str, repo_dir: str):
    path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {MODULE_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(module)
    finally:
        sys.path.remove(repo_dir)
    return module


def _resolve_dir(repo_dir: str, source: str, force_reload: bool) -> str:
    if source == "local":
        return repo_dir
    if source in ("github", "gitee"):
        raise RuntimeError(
            f"hub source {source!r} needs network egress, which this build "
            "does not have. Clone the repo on a connected machine and use "
            "source='local' with its path.")
    raise ValueError(
        f"Unknown source: \"{source}\". Allowed values: \"github\", "
        "\"gitee\", \"local\".")


def _load_entry_from_hubconf(m, name: str):
    if not isinstance(name, str):
        raise ValueError("Invalid input: model should be a str of function "
                         "name")
    func = getattr(m, name, None)
    if func is None or not callable(func):
        raise RuntimeError(f"Cannot find callable {name} in hubconf")
    return func


def list(repo_dir: str, source: str = "github",
         force_reload: bool = False) -> List[str]:
    """Entrypoint names exported by the repo's hubconf.py (reference
    hub.py:175)."""
    repo_dir = _resolve_dir(repo_dir, source, force_reload)
    module = _import_module(MODULE_HUBCONF.split(".")[0], repo_dir)
    return [f for f in dir(module)
            if callable(getattr(module, f)) and not f.startswith("_")]


def help(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False) -> str:
    """Docstring of one entrypoint (reference hub.py:223)."""
    repo_dir = _resolve_dir(repo_dir, source, force_reload)
    module = _import_module(MODULE_HUBCONF.split(".")[0], repo_dir)
    return _load_entry_from_hubconf(module, model).__doc__


def load(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False, **kwargs):
    """Instantiate an entrypoint (reference hub.py:269)."""
    repo_dir = _resolve_dir(repo_dir, source, force_reload)
    module = _import_module(MODULE_HUBCONF.split(".")[0], repo_dir)
    return _load_entry_from_hubconf(module, model)(**kwargs)
