"""FLOPs estimation (reference: python/paddle/hapi/dynamic_flops.py — per-op
handlers over forward hooks). Counts multiply-accumulates as 2 FLOPs/MAC for
matmul/conv (the MFU convention bench.py uses)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["flops"]


def _out_shape(out):
    o = out[0] if isinstance(out, (list, tuple)) else out
    return tuple(o.shape) if isinstance(o, Tensor) else ()


def _count(layer, inp, out) -> int:
    name = type(layer).__name__
    oshape = _out_shape(out)
    if not oshape:
        return 0
    n_out = int(np.prod(oshape))
    if name == "Linear":
        return 2 * n_out * int(layer.weight.shape[0])
    if name.startswith("Conv"):
        w = layer.weight  # [out_c, in_c/groups, *k]
        per_out = 2 * int(np.prod(w.shape[1:]))
        return n_out * per_out
    if name in ("ReLU", "GELU", "Sigmoid", "Tanh", "Softmax", "SiLU"):
        return n_out
    if "Norm" in name:
        return 5 * n_out
    if name in ("AvgPool2D", "MaxPool2D", "AdaptiveAvgPool2D"):
        return n_out
    return 0


def flops(net: Layer, input_size=None, inputs=None, custom_ops=None,
          print_detail=False) -> int:
    total = [0]
    hooks = []
    custom_ops = custom_ops or {}

    def attach(layer):
        for sub in layer._sub_layers.values():
            if sub._sub_layers:
                attach(sub)
            else:
                def hook(l, i, o):
                    fn = custom_ops.get(type(l))
                    total[0] += int(fn(l, i, o)) if fn else _count(l, i, o)

                hooks.append(sub.register_forward_post_hook(hook))

    attach(net)
    try:
        if inputs is not None:
            xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
            net(*xs)
        else:
            shape = tuple(1 if d in (None, -1) else d for d in input_size)
            net(Tensor(np.zeros(shape, np.float32)))
    finally:
        for h in hooks:
            h.remove()
    if print_detail:
        print(f"Total FLOPs: {total[0]:,}")
    return total[0]
