"""paddle.hapi parity (reference: python/paddle/hapi/)."""
from . import callbacks
from .dynamic_flops import flops
from .model import Model
from .model_summary import summary

__all__ = ["Model", "summary", "flops", "callbacks"]
