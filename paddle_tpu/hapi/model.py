"""paddle.Model — the Keras-like high-level API.

Reference: python/paddle/hapi/model.py:1050 (Model), fit :1741,
DynamicGraphAdapter.train_batch :817. The reference carries two adapters
(dygraph vs static graph); under a tracing runtime only the imperative
adapter exists, with paddle_tpu.jit.to_static available for compiled serving.
"""
from __future__ import annotations

import os
import pickle
from typing import Any, List, Optional, Sequence

import numpy as np

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..metric import Metric
from ..nn.layer.layers import Layer
from .callbacks import config_callbacks

__all__ = ["Model"]


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _to_tensor(x):
    if isinstance(x, Tensor):
        return x
    return Tensor(np.asarray(x))


class Model:
    """reference hapi/model.py:1050 parity."""

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False

    # -- configuration -----------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        if loss is not None and not (isinstance(loss, Layer) or callable(loss)):
            raise TypeError(
                "'loss' must be sub classes of `paddle.nn.Layer` or any "
                "callable function.")
        self._loss = loss
        for m in _to_list(metrics):
            if not isinstance(m, Metric):
                raise TypeError(
                    f"{type(m).__name__} is not a valid paddle.metric.Metric")
        self._metrics = _to_list(metrics)
        self._amp_level = None
        if isinstance(amp_configs, str):
            self._amp_level = amp_configs
        elif isinstance(amp_configs, dict):
            self._amp_level = amp_configs.get("level")

    # -- single-batch ops ---------------------------------------------------
    def _compute_loss(self, outputs, labels):
        outs = _to_list(outputs)
        labs = _to_list(labels)
        if self._loss is None:
            raise RuntimeError("loss not set; call prepare(loss=...)")
        loss = self._loss(*(outs + labs))
        if isinstance(loss, (list, tuple)):
            loss = sum(l.sum() for l in loss)
        if loss.ndim > 0:
            loss = loss.mean()
        return loss

    def train_batch(self, inputs, labels=None, update=True):
        """reference model.py DynamicGraphAdapter.train_batch:817."""
        self.network.train()
        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        labels = [_to_tensor(y) for y in _to_list(labels)]

        if self._amp_level in ("O1", "O2"):
            from .. import amp as amp_mod

            with amp_mod.auto_cast(level=self._amp_level):
                outputs = self.network(*inputs)
                loss = self._compute_loss(outputs, labels)
        else:
            outputs = self.network(*inputs)
            loss = self._compute_loss(outputs, labels)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        if metrics:
            return [float(loss)], metrics
        return [float(loss)]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        labels = [_to_tensor(y) for y in _to_list(labels)]
        with no_grad():
            outputs = self.network(*inputs)
            loss = self._compute_loss(outputs, labels) if self._loss else None
        metrics = self._update_metrics(outputs, labels)
        losses = [] if loss is None else [float(loss)]
        # always (losses, metrics) when metrics exist so _pack_logs can't
        # mislabel a metric value as the loss
        if metrics:
            return (losses, metrics)
        return losses

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        with no_grad():
            out = self.network(*inputs)
        return [o.numpy() for o in _to_list(out)]

    def _update_metrics(self, outputs, labels):
        res = []
        outs = _to_list(outputs)
        for m in self._metrics:
            stats = m.compute(*(outs + labels))
            r = m.update(*_to_list(stats))
            res.append(r)
        return res

    # -- loops --------------------------------------------------------------
    def _make_loader(self, data, batch_size, shuffle, num_workers):
        from ..io import DataLoader, Dataset

        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers)
        return data  # assume iterable of batches

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        """reference model.py fit:1741."""
        loader = self._make_loader(train_data, batch_size, shuffle,
                                   num_workers)
        eval_loader = self._make_loader(eval_data, batch_size, False,
                                        num_workers)
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks = config_callbacks(
            callbacks, model=self, batch_size=batch_size, epochs=epochs,
            steps=steps, log_freq=log_freq, verbose=verbose,
            save_freq=save_freq, save_dir=save_dir, metrics=self._metrics)
        self.stop_training = False
        cbks.on_train_begin()
        it = 0
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            update = True
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                ins, labs = self._split_batch(batch)
                # always step on the epoch's last batch (reference
                # model.py:2320): with accumulation and an epoch length not
                # divisible by accumulate_grad_batches, tail-batch grads
                # would otherwise leak into the next epoch
                update = ((step + 1) % accumulate_grad_batches == 0
                          or (steps is not None and step + 1 == steps))
                out = self.train_batch(ins, labs, update=update)
                logs = self._pack_logs(out)
                # ACTUAL rows in this batch (reference fit:1870 passes
                # batch_size in logs) — the tail batch can be short, and
                # throughput consumers must not bill the configured size
                try:
                    logs["batch_size"] = int(ins[0].shape[0])
                except Exception:
                    pass
                # with grad accumulation only every k-th batch is an
                # optimizer step; metric consumers must not count 4x
                logs["optimizer_step"] = bool(update)
                cbks.on_train_batch_end(step, logs)
                it += 1
                if num_iters is not None and it >= num_iters:
                    self.stop_training = True
                    break
            if not update:
                # iterable loaders (no __len__) can end mid-accumulation:
                # flush the pending grads so they don't leak into next epoch
                self._optimizer.step()
                self._optimizer.clear_grad()
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, batch_size=batch_size,
                              verbose=verbose, callbacks=cbks,
                              _inner=True)
        cbks.on_train_end()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None,
                 _inner=False):
        loader = self._make_loader(eval_data, batch_size, False, num_workers)
        cbks = callbacks if _inner else config_callbacks(
            callbacks, model=self, batch_size=batch_size, verbose=verbose,
            metrics=self._metrics, mode="eval")
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        logs = {}
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            ins, labs = self._split_batch(batch)
            out = self.eval_batch(ins, labs)
            logs = self._pack_logs(out)
            cbks.on_eval_batch_end(step, logs)
        # final accumulated metric values
        for m in self._metrics:
            logs[m.name()[0] if isinstance(m.name(), list) else m.name()] = (
                m.accumulate())
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False, num_workers)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch, has_label=False)
            outputs.append(self.predict_batch(ins))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    def _split_batch(self, batch, has_label=True):
        if isinstance(batch, (list, tuple)):
            if has_label and len(batch) >= 2:
                return batch[:-1] if len(batch) > 2 else [batch[0]], [batch[-1]]
            return list(batch), []
        return [batch], []

    def _pack_logs(self, out):
        logs = {}
        if isinstance(out, tuple):
            losses, metrics = out
            if losses:
                logs["loss"] = losses[0]
            for m, r in zip(self._metrics, metrics):
                name = m.name()
                logs[name[0] if isinstance(name, list) else name] = r
        elif isinstance(out, list) and out:
            logs["loss"] = out[0]
        return logs

    # -- io ------------------------------------------------------------------
    def save(self, path: str, training: bool = True):
        """reference model.py save: params + optimizer state (training=True)
        or inference artifact via jit (training=False)."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        from ..framework import io as fio

        if training:
            fio.save(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                fio.save(self._optimizer.state_dict(), path + ".pdopt")
        else:
            from .. import jit

            jit.save(self.network, path, input_spec=self._inputs)

    def load(self, path: str, skip_mismatch: bool = False, reset_optimizer=False):
        from ..framework import io as fio

        sd = fio.load(path + ".pdparams")
        self.network.set_state_dict(sd)
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)):
            self._optimizer.set_state_dict(fio.load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary

        return summary(self.network, input_size, dtypes=dtype)
