"""Model summary (reference: python/paddle/hapi/model_summary.py — layer
table with output shapes and param counts via forward hooks)."""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["summary"]


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Prints the per-layer table; returns {'total_params', 'trainable_params'}."""
    rows = []
    hooks = []

    def register(layer, prefix=""):
        for name, sub in layer._sub_layers.items():
            full = f"{prefix}{name}"
            if sub._sub_layers:
                register(sub, full + ".")
            else:
                def hook(l, inp, out, _full=full):
                    shape = None
                    o = out[0] if isinstance(out, (list, tuple)) else out
                    if isinstance(o, Tensor):
                        shape = tuple(o.shape)
                    n = sum(int(np.prod(p.shape))
                            for _, p in l.named_parameters())
                    rows.append((_full, type(l).__name__, shape, n))

                hooks.append(sub.register_forward_post_hook(hook))

    register(net)
    try:
        if input is not None:
            x = input if isinstance(input, (list, tuple)) else [input]
            net(*x)
        elif input_size is not None:
            sizes = (input_size if isinstance(input_size, list)
                     else [input_size])
            dts = dtypes if isinstance(dtypes, (list, tuple)) else [
                dtypes] * len(sizes)
            args = []
            for s, dt in zip(sizes, dts):
                s = tuple(1 if d in (None, -1) else d for d in s)
                args.append(Tensor(np.zeros(s, dtype=np.dtype(dt or "float32"))))
            net(*args)
    finally:
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for _, p in net.named_parameters())
    trainable = sum(int(np.prod(p.shape)) for _, p in net.named_parameters()
                    if p.trainable)
    w = 76
    print("-" * w)
    print(f"{'Layer (type)':<36}{'Output Shape':<24}{'Param #':>14}")
    print("=" * w)
    for name, cls, shape, n in rows:
        print(f"{name + ' (' + cls + ')':<36}{str(shape):<24}{n:>14,}")
    print("=" * w)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print("-" * w)
    return {"total_params": total, "trainable_params": trainable}
