"""High-level API callbacks (reference: python/paddle/hapi/callbacks.py —
Callback/CallbackList, ProgBarLogger, ModelCheckpoint, EarlyStopping,
LRScheduler; VisualDL omitted: no visualdl on TPU hosts, kept as stub)."""
from __future__ import annotations

import numbers
import os
import time
from typing import List, Optional

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "VisualDL", "MonitorCallback", "config_callbacks"]


class Callback:
    """reference callbacks.py Callback — every hook is optional."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    # train/eval/predict lifecycle hooks
    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def __iter__(self):
        return iter(self.callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *a: self._call(name, *a)
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """reference callbacks.py ProgBarLogger: periodic loss/metric lines."""

    def __init__(self, log_freq: int = 10, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.steps = self.params.get("steps")

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._start = time.time()
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")

    def _fmt(self, logs):
        out = []
        for k, v in (logs or {}).items():
            if k in ("batch_size", "optimizer_step"):  # metadata
                continue
            if isinstance(v, (numbers.Number, np.floating)):
                out.append(f"{k}: {float(v):.4f}")
            elif isinstance(v, (list, tuple)) and v and isinstance(
                    v[0], numbers.Number):
                out.append(f"{k}: " + "/".join(f"{float(x):.4f}" for x in v))
        return " - ".join(out)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose == 2 and (step + 1) % self.log_freq == 0:
            print(f"step {step + 1}/{self.steps or '?'} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            print(f"epoch {epoch + 1} done ({dt:.1f}s) - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """reference callbacks.py ModelCheckpoint: save every N epochs + final."""

    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """reference callbacks.py EarlyStopping (monitor/patience/min_delta)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode not in ("auto", "min", "max"):
            mode = "auto"
        if mode == "min" or (mode == "auto" and "acc" not in monitor):
            self.monitor_op = np.less
            self.min_delta *= -1
        else:
            self.monitor_op = np.greater
        self.best_value = np.inf if self.monitor_op == np.less else -np.inf
        self.wait_epoch = 0

    def on_train_begin(self, logs=None):
        self.wait_epoch = 0
        if self.baseline is not None:
            self.best_value = self.baseline

    def on_eval_end(self, logs=None):
        if logs is None or self.monitor not in logs:
            return
        current = logs[self.monitor]
        if isinstance(current, (list, tuple)):
            current = current[0]
        current = float(current)
        if self.monitor_op(current - self.min_delta, self.best_value):
            self.best_value = current
            self.wait_epoch = 0
            if self.save_best_model and self.params.get("save_dir"):
                self.model.save(
                    os.path.join(self.params["save_dir"], "best_model"))
        else:
            self.wait_epoch += 1
        if self.wait_epoch > self.patience:
            self.model.stop_training = True
            if self.verbose:
                print(f"Early stopping: {self.monitor} did not improve for "
                      f"{self.patience} evals")


class LRScheduler(Callback):
    """reference callbacks.py LRScheduler: steps the optimizer's LR scheduler."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()


class VisualDL(Callback):
    """Stub: visualdl is GPU-stack tooling; scalars are appended to a jsonl
    file instead so training curves remain recoverable."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        import json

        os.makedirs(self.log_dir, exist_ok=True)
        with open(os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            rec = {"step": self._step}
            for k, v in (logs or {}).items():
                if k in ("batch_size", "optimizer_step"):  # metadata
                    continue
                if isinstance(v, (int, float, np.floating)):
                    rec[k] = float(v)
            f.write(json.dumps(rec) + "\n")
        self._step += 1


_FINISHED_FIT_LABELS: List[str] = []  # sessions awaiting series cleanup


class MonitorCallback(Callback):
    """Feed ``Model.fit`` training telemetry into ``paddle_tpu.monitor``:
    step-time histogram, samples/sec + steps/sec throughput gauges, step
    and sample counters, and — when the per-sample cost is known — MFU.

    ``flops_per_sample`` is the model's forward+backward FLOPs for ONE
    sample (≈ 6 * params for a dense transformer LM over its sequence);
    ``peak_flops_per_sec`` is the accelerator's peak (e.g. 197e12 for a
    v5e chip in bf16). Both must be given for the MFU gauge; neither is
    guessed — a wrong denominator is worse than no MFU.

    ``config_callbacks`` installs this automatically whenever the
    monitor is enabled, so a plain ``Model.fit`` run already exports
    throughput; off-monitor it no-ops per batch after one bool check.
    """

    def __init__(self, flops_per_sample: Optional[float] = None,
                 peak_flops_per_sec: Optional[float] = None):
        super().__init__()
        self.flops_per_sample = flops_per_sample
        self.peak_flops_per_sec = peak_flops_per_sec
        self._t0 = None
        self._fit_label = None  # assigned per train session

    def _monitor(self):
        from .. import monitor

        return monitor if monitor.enabled() else None

    _GAUGES = (
        ("paddle_tpu_train_throughput_samples_per_sec",
         "instantaneous Model.fit throughput (latest batch), per fit "
         "session"),
        ("paddle_tpu_train_throughput_batches_per_sec",
         "instantaneous train_batch rate (latest batch; equals optimizer "
         "steps/sec only without grad accumulation), per fit session"),
        ("paddle_tpu_train_mfu_ratio",
         "model FLOPs utilization: achieved / peak, per fit session"),
    )

    def _fit_gauge(self, mon, idx):
        name, help_ = self._GAUGES[idx]
        return mon.gauge(name, help_, ("fit",))

    def on_train_begin(self, logs=None):
        mon = self._monitor()
        if mon is not None:
            # per-session gauge label: two concurrently fitting Models
            # in one process must not clobber each other's throughput
            # (same idiom as the engine/loader/pool labels). The series
            # deliberately OUTLIVES fit so the final throughput stays
            # visible in post-run snapshots — cleanup of FINISHED
            # sessions is deferred to the next fit, which bounds
            # cardinality at live sessions + one
            while _FINISHED_FIT_LABELS:
                stale = _FINISHED_FIT_LABELS.pop()
                for i in range(len(self._GAUGES)):
                    self._fit_gauge(mon, i).remove(fit=stale)
            self._fit_label = mon.instance_label("fit")

    def on_train_end(self, logs=None):
        if self._fit_label is not None:
            _FINISHED_FIT_LABELS.append(self._fit_label)

    def on_train_batch_begin(self, step, logs=None):
        self._t0 = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        mon = self._monitor()
        # the flag gate is EXPLICIT at this per-batch seam (PT005):
        # _monitor() already returns None while disabled, but the
        # enabled() check keeps the near-zero-when-off contract visible
        # (and correct even for a caller holding a stale module ref)
        if mon is None or not mon.enabled() or self._t0 is None:
            return
        if self._fit_label is None:  # monitor enabled mid-session
            self._fit_label = mon.instance_label("fit")
        dt = time.perf_counter() - self._t0
        # the fit loop reports the ACTUAL row count per batch (tail
        # batches can be short); configured size is only the fallback
        batch_size = ((logs or {}).get("batch_size")
                      or self.params.get("batch_size") or 1)
        mon.histogram(
            "paddle_tpu_train_step_seconds",
            "wall time of one train_batch (forward+backward, plus the "
            "update on optimizer-step batches)").observe(dt)
        mon.counter("paddle_tpu_train_batches_total",
                    "train_batch calls run by Model.fit").inc()
        if (logs or {}).get("optimizer_step", True):
            # with grad accumulation only every k-th batch steps the
            # optimizer — the steps counter must reflect that
            mon.counter("paddle_tpu_train_steps_total",
                        "optimizer steps run by Model.fit").inc()
        mon.counter("paddle_tpu_train_samples_total",
                    "samples consumed by Model.fit").inc(batch_size)
        sps = batch_size / dt if dt > 0 else 0.0
        self._fit_gauge(mon, 0).labels(fit=self._fit_label).set(sps)
        self._fit_gauge(mon, 1).labels(fit=self._fit_label).set(
            1.0 / dt if dt > 0 else 0.0)
        if self.flops_per_sample and self.peak_flops_per_sec:
            self._fit_gauge(mon, 2).labels(fit=self._fit_label).set(
                sps * self.flops_per_sample / self.peak_flops_per_sec)


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=10, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    """reference callbacks.py config_callbacks: install defaults."""
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    from .. import monitor

    if monitor.enabled() and not any(
            isinstance(c, MonitorCallback) for c in cbks):
        cbks = cbks + [MonitorCallback()]
    cb_list = CallbackList(cbks)
    cb_list.set_model(model)
    params = {
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "log_freq": log_freq, "verbose": verbose, "metrics": metrics or [],
        "save_dir": save_dir,
    }
    cb_list.set_params(params)
    return cb_list


class ReduceLROnPlateau(Callback):
    """Reduce LR when a monitored metric plateaus (reference
    hapi/callbacks.py ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10,
                 verbose=1, mode="auto", min_delta=1e-4, cooldown=0,
                 min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "min" or (mode == "auto" and "acc" not in monitor):
            self._is_better = lambda cur, best: cur < best - self.min_delta
            self.best = float("inf")
        else:
            self._is_better = lambda cur, best: cur > best + self.min_delta
            self.best = -float("inf")
        self.cooldown_counter = 0
        self.wait = 0

    def _get_value(self, logs):
        v = (logs or {}).get(self.monitor)
        if isinstance(v, (list, tuple)):
            v = v[0]
        return v

    def on_eval_end(self, logs=None):
        self._step(self._get_value(logs))

    def on_epoch_end(self, epoch, logs=None):
        self._step(self._get_value(logs))

    def _step(self, current):
        if current is None:
            return
        current = float(current)
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self._is_better(current, self.best):
            self.best = current
            self.wait = 0
            return
        self.wait += 1
        if self.wait < self.patience or self.cooldown_counter > 0:
            return
        opt = getattr(self.model, "_optimizer", None)
        if opt is None:
            return
        old = float(opt.get_lr())
        new = max(old * self.factor, self.min_lr)
        if old - new > 1e-12:
            opt.set_lr(new)
            if self.verbose:
                print(f"ReduceLROnPlateau: lr {old:.3g} -> {new:.3g}")
        self.cooldown_counter = self.cooldown
        self.wait = 0


class WandbCallback(Callback):
    """Weights & Biases logging callback (reference hapi/callbacks.py
    WandbCallback). wandb is not bundled (zero-egress image) — the
    constructor raises with instructions rather than failing at first
    log."""

    def __init__(self, *args, **kwargs):
        try:
            import wandb  # noqa: F401
        except ImportError as e:
            raise ModuleNotFoundError(
                "WandbCallback requires the `wandb` package, which is not "
                "bundled in this image (no network egress); install it on "
                "a connected machine.") from e


__all__ += ["ReduceLROnPlateau", "WandbCallback"]
