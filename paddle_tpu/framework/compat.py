"""Small top-level compatibility APIs (reference python/paddle/__init__.py
long tail: batch, LazyGuard, check_shape, set_printoptions, tolist,
function-form in-place ops, signal handling)."""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["batch", "LazyGuard", "check_shape", "disable_signal_handler",
           "set_printoptions", "tolist", "dtype", "pow_", "scatter_",
           "index_add_", "index_put_",
           "squeeze_", "tanh_", "unsqueeze_"]

# paddle.dtype is the type of dtype objects; here dtypes are jnp.dtype
dtype = jnp.dtype


def batch(reader, batch_size, drop_last=False):
    """Legacy batched-reader decorator (python/paddle/batch.py)."""

    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


class LazyGuard:
    """reference LazyGuard defers parameter initialization until first use;
    initialization here is cheap host-side numpy/jax — eager init inside the
    scope keeps semantics (params exist after construction) with no cost
    worth deferring, so the guard is a no-op context."""

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def check_shape(shape):
    """Validate a shape argument (static-graph helper)."""
    for s in list(shape):
        if not isinstance(s, (int, np.integer)) and not hasattr(s, "dtype"):
            raise TypeError(f"shape entries must be int, got {type(s)}")


def disable_signal_handler():
    """The reference unhooks its C++ SIGSEGV handlers; this runtime installs
    none, so there is nothing to disable."""
    return None


_PRINT_OPTS = {}


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Tensor print formatting (python/paddle/tensor/to_string.py)."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _PRINT_OPTS.update(kw)
    np.set_printoptions(**kw)


def tolist(x):
    """paddle.tolist parity."""
    return np.asarray(x._value if isinstance(x, Tensor) else x).tolist()


def _fn_inplace(name):
    def f(x, *args, **kwargs):
        return getattr(x, name)(*args, **kwargs)

    f.__name__ = name
    return f


pow_ = _fn_inplace("pow_")
index_add_ = _fn_inplace("index_add_")
index_put_ = _fn_inplace("index_put_")
scatter_ = _fn_inplace("scatter_")
squeeze_ = _fn_inplace("squeeze_")
tanh_ = _fn_inplace("tanh_")
unsqueeze_ = _fn_inplace("unsqueeze_")
