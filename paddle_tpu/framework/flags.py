"""Flag registry (phi/core/flags.cc + pybind/global_value_getter_setter.cc parity).

A typed registry with FLAGS_* environment-variable override — the reference's
1,270-line PHI_DEFINE_EXPORTED_* corpus collapses to the flags that have
meaning on TPU/XLA; unknown flags are accepted (stored) so reference scripts
calling set_flags don't break.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Union

__all__ = ["get_flags", "set_flags", "define_flag"]

_REGISTRY: Dict[str, Any] = {}


def define_flag(name: str, default, help_: str = ""):
    env = os.environ.get(name)
    if env is not None:
        if isinstance(default, bool):
            default = env.lower() in ("1", "true", "yes")
        elif isinstance(default, int):
            default = int(env)
        elif isinstance(default, float):
            default = float(env)
        else:
            default = env
    _REGISTRY[name] = default
    return default


# the flags that matter for the TPU runtime (reference analogs noted)
define_flag("FLAGS_check_nan_inf", False)          # eager/nan_inf_utils.cc:83
define_flag("FLAGS_allocator_strategy", "auto_growth")
define_flag("FLAGS_fraction_of_gpu_memory_to_use", 0.92)
define_flag("FLAGS_cudnn_deterministic", False)
define_flag("FLAGS_embedding_deterministic", 0)
define_flag("FLAGS_benchmark", False)
define_flag("FLAGS_use_pallas_kernels", True)      # TPU-native: route fused ops to Pallas
define_flag("FLAGS_flash_head_batched", False)    # BSHD-native flash (opt-in until TPU-measured)
define_flag("FLAGS_use_autotune", True)            # kernel autotune cache (ops/autotune.py)
define_flag("FLAGS_log_level", 0)
define_flag("FLAGS_enable_monitor", False)         # paddle_tpu.monitor metrics registry
define_flag("FLAGS_enable_trace", False)           # paddle_tpu.tracing request recorder
define_flag("FLAGS_enable_ledger", False)          # paddle_tpu.monitor.ledger program ledger


def get_flags(flags: Union[str, List[str]]):
    if isinstance(flags, str):
        flags = [flags]
    return {f: _REGISTRY.get(f) for f in flags}


def set_flags(flags: Dict[str, Any]):
    for k, v in flags.items():
        _REGISTRY[k] = v
    # live toggles: flags that runtime components read per-op are pushed to
    # their owners here (the pybind global_value_getter_setter analog)
    if "FLAGS_check_nan_inf" in flags:
        from ..core.amp_state import amp_state

        amp_state.check_nan_inf = bool(flags["FLAGS_check_nan_inf"])
    if "FLAGS_enable_monitor" in flags:
        from ..monitor import _sync_enabled

        _sync_enabled(bool(flags["FLAGS_enable_monitor"]))
    if "FLAGS_enable_trace" in flags:
        from ..tracing import _sync_enabled as _sync_trace

        _sync_trace(bool(flags["FLAGS_enable_trace"]))
    if "FLAGS_enable_ledger" in flags:
        from ..monitor.ledger import _sync_enabled as _sync_ledger

        _sync_ledger(bool(flags["FLAGS_enable_ledger"]))
