"""paddle.save / paddle.load (python/paddle/framework/io.py:646,888 parity).

Serialization contract matches the reference: pickle files holding nested
dicts of numpy arrays (state_dict key compatibility for porting weights),
with >4GB protocol-4 chunked writes handled by pickle itself. Tensors are
converted to numpy on save and restored as Tensors on load.

For sharded/distributed checkpoints see paddle_tpu.distributed.checkpoint
(tensorstore-style sharded layout, SURVEY.md §5.4 TPU design note).
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from ..core.tensor import Tensor

__all__ = ["save", "load"]

_PROTOCOL = 4


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        arr = np.asarray(obj.value)
        return _TensorPayload(arr, obj.name,
                              trainable=not obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        if hasattr(obj, "_fields"):  # namedtuple
            return t(*(_to_saveable(v) for v in obj))
        return t(_to_saveable(v) for v in obj)
    return obj


class _TensorPayload:
    """Tagged tensor payload so load() can restore Tensor objects."""

    def __init__(self, array, name=None, trainable=False):
        self.array = array
        self.name = name
        self.trainable = trainable


def _from_saved(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        t = Tensor(np.asarray(obj.array), stop_gradient=not obj.trainable,
                   name=obj.name)
        return t
    if isinstance(obj, dict):
        return {k: _from_saved(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        if hasattr(obj, "_fields"):
            return t(*(_from_saved(v, return_numpy) for v in obj))
        return t(_from_saved(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = _PROTOCOL, **configs):
    if isinstance(path, str):
        dirname = os.path.dirname(path)
        if dirname and not os.path.exists(dirname):
            os.makedirs(dirname, exist_ok=True)
    payload = _to_saveable(obj)
    with open(path, "wb") if isinstance(path, str) else path as f:
        pickle.dump(payload, f, protocol=max(protocol, 4))


def load(path: str, **configs) -> Any:
    return_numpy = configs.get("return_numpy", False)
    with open(path, "rb") if isinstance(path, str) else path as f:
        payload = pickle.load(f)
    return _from_saved(payload, return_numpy)
