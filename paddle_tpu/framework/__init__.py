"""paddle.framework parity (python/paddle/framework/__init__.py)."""
from ..core.dtype import get_default_dtype, set_default_dtype  # noqa: F401
from ..core.random import seed  # noqa: F401
from ..nn.parameter import Parameter  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401
from .io import load, save  # noqa: F401


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """paddle.create_parameter parity (python/paddle/tensor/creation.py)."""
    from ..nn.initializer import Constant, XavierUniform
    from ..nn.param_attr import ParamAttr
    from ..core import dtype as dtypes

    attr = ParamAttr._to_attr(attr)
    init = attr.initializer or default_initializer or (
        Constant(0.0) if is_bias else XavierUniform())
    d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
    value = init(shape, d)
    return Parameter(value, trainable=attr.trainable, name=attr.name or name,
                     learning_rate=attr.learning_rate,
                     regularizer=attr.regularizer, need_clip=attr.need_clip)


def in_dygraph_mode() -> bool:
    return True
