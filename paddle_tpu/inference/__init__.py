"""Serving / inference engine.

TPU-native re-design of the reference's AnalysisPredictor stack
(``paddle/fluid/inference/api/analysis_predictor.h:94`` Run at ``:148``,
AnalysisConfig, pass pipeline): the IR-pass pipeline + TensorRT subgraph
capture collapse into one AOT XLA compile (``jax.jit(...).lower().
compile()``); the serialized artifact is StableHLO via ``jax.export``
(``*.pdmodel`` analog), weights ride the ``state_dict`` pickle
(``*.pdiparams``). See DESIGN.md for the TensorRT descope rationale.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["Config", "Predictor", "create_predictor", "convert_to_mixed_precision",
           "PrecisionType", "PlaceType", "PagedKVCache"]

from .paged_cache import PagedKVCache  # noqa: E402


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PlaceType:
    CPU = "cpu"
    GPU = "tpu"   # reference GPU place maps onto the accelerator
    XPU = "tpu"


class Config:
    """≙ AnalysisConfig (inference/api/paddle_analysis_config.h).

    Knobs that steer CUDA/TRT/MKLDNN pass pipelines in the reference are
    accepted for compatibility and recorded; on TPU the optimization
    pipeline IS the XLA compile, so most are no-ops by design.
    """

    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        self.model_path = model_path
        self.params_path = params_path or (
            model_path + ".pdiparams" if model_path else None)
        self._device = "tpu" if any(
            d.platform == "tpu" for d in jax.devices()) else "cpu"
        self._precision = PrecisionType.Float32
        self._memory_optim = True
        self._ir_optim = True
        self._flags: Dict[str, Any] = {}

    # -- device selection ---------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100,
                       device_id: int = 0,
                       precision=PrecisionType.Float32):
        self._device = "tpu"
        self._precision = precision

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self) -> bool:
        return self._device != "cpu"

    # -- compat no-ops (XLA owns fusion/memory planning) ---------------------
    def switch_ir_optim(self, flag: bool = True):
        self._ir_optim = flag

    def enable_memory_optim(self, flag: bool = True):
        self._memory_optim = flag

    def enable_tensorrt_engine(self, *a, **kw):
        raise NotImplementedError(
            "TensorRT is NVIDIA-specific; the TPU serving path is AOT XLA "
            "compilation (see DESIGN.md descope table)")

    def enable_mkldnn(self):
        pass

    def set_cpu_math_library_num_threads(self, n: int):
        self._flags["cpu_threads"] = n

    def summary(self) -> str:
        return (f"Config(model={self.model_path}, device={self._device}, "
                f"precision={self._precision})")


class Predictor:
    """≙ AnalysisPredictor (analysis_predictor.h:94).

    Two construction modes:
    - from a ``Config`` pointing at a ``paddle_tpu.jit.save`` artifact
      (state_dict + exported StableHLO when present);
    - directly from a Layer + example inputs (``Predictor.from_layer``) —
      AOT-compiles the forward.
    """

    def __init__(self, config: Config):
        self.config = config
        self._fn = None
        self._params = None
        self._inputs: Dict[str, np.ndarray] = {}
        self._input_names: List[str] = []
        self._outputs: List[Any] = []
        if config.model_path:
            self._load(config.model_path)

    # -- loading -------------------------------------------------------------
    def _load(self, path: str):
        from .aot import load_exported

        exported = None
        if os.path.exists(path + ".stablehlo"):
            exported = load_exported(path + ".stablehlo")
        params = None
        if os.path.exists(self.config.params_path or ""):
            from ..framework.io import load as fload

            params = fload(self.config.params_path)
        if exported is None and params is None:
            raise FileNotFoundError(
                f"no serving artifact at {path} (.stablehlo/.pdiparams)")
        self._exported = exported
        if params is not None:
            from ..core.tensor import Tensor as _T

            params = {k: (v.value if isinstance(v, _T) else jnp.asarray(v))
                      for k, v in params.items()}
        self._params = params
        if exported is not None:
            # jit.save exports fwd(params, *inputs): weights stay in the
            # .pdiparams pickle instead of being baked into the StableHLO
            if params is None:
                raise FileNotFoundError(
                    f"{self.config.params_path}: exported program needs its "
                    "weights file")
            self._fn = lambda *xs: exported.call(params, *xs)
            self._input_names = [f"x{i}"
                                 for i in range(len(exported.in_avals) - 1)]
        else:
            # params-only artifact (jit.save without input_spec exports no
            # program): fail here, not with a TypeError at first run()
            raise FileNotFoundError(
                f"{path}.stablehlo missing: the artifact has weights but no "
                "exported program — re-save with jit.save(layer, path, "
                "input_spec=[...]) to emit one")

    @classmethod
    def from_layer(cls, layer, example_inputs: Sequence[Any],
                   precision: Optional[str] = None):
        """AOT-compile ``layer(*example_inputs)``; the predictor then runs
        the compiled executable (no retracing at serve time)."""
        from ..nn.functional_call import functional_call

        self = cls.__new__(cls)
        self.config = Config()
        self._inputs = {}
        self._outputs = []
        params = {k: p.value for k, p in layer.named_parameters()}
        if precision is not None:
            dt = jnp.dtype(precision)
            params = {k: (v.astype(dt)
                          if jnp.issubdtype(v.dtype, jnp.floating) else v)
                      for k, v in params.items()}

        def fwd(params, *xs):
            return functional_call(layer, params,
                                   *[Tensor(x) for x in xs])

        exam = [np.asarray(x.value if isinstance(x, Tensor) else x)
                for x in example_inputs]
        jitted = jax.jit(fwd)
        self._compiled = jitted.lower(params, *exam).compile()
        self._params = params
        self._fn = lambda *xs: self._compiled(params, *xs)
        self._input_names = [f"x{i}" for i in range(len(exam))]
        return self

    # -- AnalysisPredictor-shaped API -----------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str):
        return _Handle(self._inputs, name)

    def get_output_names(self) -> List[str]:
        return [f"out{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name: str):
        idx = int(name.replace("out", "") or 0)
        return _OutHandle(self, idx)

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """Execute the compiled program. Either pass inputs directly
        (functional style, returns numpy outputs) or stage them via input
        handles (reference style, returns True; read output handles)."""
        explicit = inputs is not None
        if not explicit:
            inputs = [self._inputs[n] for n in self._input_names]
        out = self._fn(*inputs)
        self._outputs = list(out) if isinstance(out, (tuple, list)) else [out]
        if explicit:
            return [np.asarray(o) for o in self._outputs]
        return True

    # -- introspection ---------------------------------------------------------
    def get_serialized_program(self) -> bytes:
        if getattr(self, "_exported", None) is not None:
            from .aot import serialize_exported

            return serialize_exported(self._exported)
        return b""


class _Handle:
    def __init__(self, store, name):
        self._store = store
        self._name = name

    def reshape(self, shape):
        pass  # shapes are taken from copy_from_cpu

    def copy_from_cpu(self, arr: np.ndarray):
        self._store[self._name] = np.asarray(arr)


class _OutHandle:
    def __init__(self, pred, idx):
        self._pred = pred
        self._idx = idx

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._pred._outputs[self._idx])

    def shape(self):
        return list(np.asarray(self._pred._outputs[self._idx]).shape)


def create_predictor(config: Config) -> Predictor:
    """≙ paddle_infer::CreatePredictor."""
    return Predictor(config)


def convert_to_mixed_precision(src_model, src_params, dst_model, dst_params,
                               mixed_precision=PrecisionType.Bfloat16,
                               backend=None, keep_io_types=True,
                               black_list=None):
    """Offline weight conversion (reference convert_to_mixed_precision):
    floating-point params cast to the target dtype, artifact re-saved."""
    from ..framework.io import load as fload
    from ..framework.io import save as fsave

    params = fload(src_params)
    dt = jnp.dtype(mixed_precision)
    out = {}
    for k, v in params.items():
        arr = v.value if isinstance(v, Tensor) else jnp.asarray(v)
        if jnp.issubdtype(arr.dtype, jnp.floating):
            arr = arr.astype(dt)
        out[k] = arr
    fsave(out, dst_params)
