"""AOT export/import of compiled programs (the ``*.pdmodel`` analog).

``jax.export`` serializes a lowered jitted function as StableHLO bytes —
portable across processes and (within compatibility windows) jax versions.
This is the deployable-artifact half of the serving story; the other half
(weights) is the ``state_dict`` pickle written by ``paddle_tpu.jit.save``.
Reference analog: AnalysisPredictor loading a ProgramDesc + params
(inference/api/analysis_predictor.h:148); here the "program" is already
compiled IR, not an op list to re-optimize.
"""
from __future__ import annotations

from typing import Any, Callable, Sequence

import jax

__all__ = ["export_fn", "save_exported", "load_exported",
           "serialize_exported"]


def export_fn(fn: Callable, *example_args, **jit_kwargs):
    """Export ``jax.jit(fn)`` at the example-argument shapes. Returns a
    jax.export.Exported (call via ``.call``)."""
    from jax import export as jexport

    jitted = jax.jit(fn, **jit_kwargs)
    shapes = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(jax.numpy.shape(x),
                                       jax.numpy.result_type(x)),
        example_args)
    return jexport.export(jitted)(*shapes)


def serialize_exported(exported) -> bytes:
    return exported.serialize()


def save_exported(exported, path: str):
    with open(path, "wb") as f:
        f.write(exported.serialize())


def load_exported(path: str):
    from jax import export as jexport

    with open(path, "rb") as f:
        return jexport.deserialize(f.read())
