"""Tensor-parallel serving mesh — shard one engine over N chips.

The training side already runs pjit meshes and shard_map
(``distributed/_spmd.py``, ``fleet/meta_parallel/``); THIS module is the
serving half: a 1-D ``Mesh`` over the ``"mp"`` axis (the same axis name
the llama layer stack's PartitionSpecs already carry, so the training
sharding plan IS the serving sharding plan) that the continuous-batching
engines shard their device state over:

- **weights** follow their layer pspecs (column-parallel q/k/v/gate/up
  on the out-dim, row-parallel o/down on the in-dim, vocab-parallel
  embedding/lm_head) — GSPMD partitions the projections and inserts
  exactly one psum per block at the row-parallel reductions;
- **KV pools / dense cache slabs / prefill minis** shard on the
  (kv_)head axis — attention is head-parallel, so the decode read never
  crosses chips; per-(page, kv_head) int8 scales shard the same way;
- **everything per-slot** (sampling vectors, spec_k, adapter_idx, lens,
  the page table) REPLICATES — the PR 2 one-program invariant is
  mesh-invariant: one compiled SPMD program serves any request mix at
  any TP degree.

The page ALLOCATOR, prefix-cache chain hashes, CoW bookkeeping, and
quota/queue logic all operate on page *indices* and host state — they
never see the mesh and need no fork (TP-invariant by construction).

Attention kernels (Pallas on TPU, jnp fallbacks on CPU) are wrapped in
``shard_map`` by their ops modules (``ops/paged_attention.py``,
``ops/_decode.py``, ``ops/pallas.py``) when the engine threads its
``tp=(mesh, axis)`` handle through the model forwards: each shard runs
the UNMODIFIED kernel on its local head slice — zero communication
inside attention, and on TPU the per-shard Mosaic kernel sees local
pools instead of forcing an all-gather of the sharded HBM pools.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

__all__ = ["TP_AXIS", "make_tp_mesh", "validate_tp_model",
           "shard_params_tp", "tp_shard_kv", "tp_replicate"]

# the serving mesh axis: "mp" on purpose — llama's ColumnParallel/
# RowParallel/VocabParallel params already carry P(..., "mp") pspecs
# from the training stack, so the engine shards weights by reading the
# annotations it finds instead of keeping a second plan
TP_AXIS = "mp"


def make_tp_mesh(tp_degree: int, devices=None) -> Optional[Mesh]:
    """Build the engine's 1-D tensor-parallel mesh (axis ``"mp"``), or
    None when ``tp_degree == 1`` (single-device engine — every program
    stays exactly the pre-TP trace).

    ``devices`` pins the replica to a device subset (ints index
    ``jax.devices()``; device objects pass through) — the
    ``ReplicaSpec(devices=...)`` seam, so an N-replica × TP-k fleet
    partitions one slice instead of every replica claiming device 0.
    A ``tp_degree == 1`` engine takes no mesh; pinning a lone device
    is the caller's ``jax.default_device`` concern."""
    if (isinstance(tp_degree, bool)
            or not isinstance(tp_degree, (int, np.integer))
            or tp_degree < 1):
        raise ValueError(
            f"tp_degree must be an int >= 1, got {tp_degree!r}")
    if tp_degree == 1:
        return None
    devs = _resolve_devices(devices)
    if devices is not None and len(devs) != tp_degree:
        # a pinned subset is the explicit fleet-partitioning seam: a
        # size mismatch is a slice typo that would silently idle chips
        # (too many) or fail later (too few) — surface it here
        raise ValueError(
            f"tp_devices pins {len(devs)} devices but tp_degree="
            f"{tp_degree} — pass exactly tp_degree devices")
    if len(devs) < tp_degree:
        raise ValueError(
            f"tp_degree={tp_degree} needs at least that many devices, "
            f"got {len(devs)} (jax.devices()) — on CPU CI run under "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=N")
    return Mesh(np.asarray(devs[:tp_degree]), (TP_AXIS,))


def _resolve_devices(devices) -> Sequence:
    if devices is None:
        return jax.devices()
    out = []
    all_devs = None
    for d in devices:
        if isinstance(d, (int, np.integer)) and not isinstance(d, bool):
            if all_devs is None:
                all_devs = jax.devices()
            if not 0 <= int(d) < len(all_devs):
                raise ValueError(
                    f"device index {d} out of range "
                    f"(0..{len(all_devs) - 1})")
            out.append(all_devs[int(d)])
        else:
            out.append(d)
    return out


def validate_tp_model(model, tp_degree: int) -> None:
    """Fail at ENGINE CONSTRUCTION — not inside a traced program — when
    the model's geometry cannot shard evenly over ``tp_degree``: query
    heads and kv heads (attention shards per head), the MLP
    intermediate (column/row split), and the vocab (vocab-parallel
    embedding/lm_head). Models without a llama-shaped ``config`` are
    let through — GSPMD will still partition what divides and
    replicate what does not."""
    cfg = getattr(model, "config", None)
    if cfg is None or tp_degree <= 1:
        return
    checks = (
        ("num_attention_heads", getattr(cfg, "num_attention_heads",
                                        None)),
        ("kv_heads", getattr(cfg, "kv_heads", None)),
        ("intermediate_size", getattr(cfg, "intermediate_size", None)),
        ("vocab_size", getattr(cfg, "vocab_size", None)),
    )
    for name, val in checks:
        if val is not None and val % tp_degree:
            raise ValueError(
                f"tp_degree={tp_degree} does not divide model "
                f"{name}={val} — the head/ffn/vocab axes must shard "
                f"evenly")


def shard_params_tp(model, params: dict, mesh: Mesh) -> dict:
    """Place every engine parameter onto the mesh by its layer pspec
    (``distributed/_spmd.set_pspec`` annotations — the training plan),
    replicated when unannotated. Returns a new name->array dict; the
    engine's jitted programs pick the shardings up as committed-input
    shardings, and GSPMD partitions the matmuls accordingly."""
    from ..distributed._spmd import _filter_spec, layer_pspecs

    specs = layer_pspecs(model)   # params + buffers, replicated when
    #                               unannotated — the one plan source
    out = {}
    for name, v in params.items():
        spec = _filter_spec(specs.get(name, P()), mesh)
        out[name] = jax.device_put(v, NamedSharding(mesh, spec))
    return out


def _kv_spec(arr) -> P:
    """PartitionSpec for one cache/pool array: 4-D K/V storage
    ``[..., ..., heads, head_dim]`` shards on the head axis (axis -2);
    2-D per-(page, kv_head) scale arrays shard on the head axis
    (axis -1); anything else replicates."""
    if arr.ndim == 4:
        return P(None, None, TP_AXIS, None)
    if arr.ndim == 2:
        return P(None, TP_AXIS)
    return P()


def tp_shard_kv(caches, mesh: Mesh):
    """Shard a per-layer cache list (dense slabs, page pools, or
    prefill minis; entries are ``(k, v)`` or int8
    ``(k, v, k_scale, v_scale)`` tuples) on the kv-head axis. Pure
    placement — values are untouched, so a sharded pool reads back
    bitwise what an unsharded one holds."""
    return [tuple(jax.device_put(a, NamedSharding(mesh, _kv_spec(a)))
                  for a in entry)
            for entry in caches]


def tp_replicate(x, mesh: Mesh):
    """Commit ``x`` to the mesh fully REPLICATED — the per-slot device
    vectors, the page table, and every host-shipped index vector take
    this path, which is what keeps the one-compiled-program invariant:
    program signatures (shapes + shardings) are identical for any
    request mix at any TP degree."""
    return jax.device_put(x, NamedSharding(mesh, P()))
