"""Autoregressive generation engine over the KV-cache decode path.

The serving counterpart of the reference's fused_multi_transformer decode
loop (``fused_multi_transformer_op.cu.h:745`` masked MHA over CacheKV; the
reference drives it token-by-token from AnalysisPredictor). TPU-native
form: ONE jitted prefill program + ONE jitted multi-token decode program
(``lax.scan`` over steps, cache carried functionally, cache buffers
donated) — token steps never leave the device, so the host round-trip
(65ms through a tunnel, ~1ms locally) is paid once per generate() call,
not once per token.
"""
from __future__ import annotations

import functools
import heapq
import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from .. import monitor
from .. import tracing as trace
from ..core.tensor import Tensor
from ..nn.functional_call import substituted_state
from .ngram import NgramIndex, NgramProposer, propose_device

__all__ = ["GenerationConfig", "CausalLMEngine",
           "ContinuousBatchingEngine",
           "PagedContinuousBatchingEngine", "prefill_buckets_for",
           "RequestFault", "EngineFault", "classify_fault",
           "REQUEST_SITES", "PagePoolExhausted", "ADMISSION_MODES",
           "NgramProposer"]


# -- fault taxonomy (serving-path blast-radius classification) ---------------
#
# At serving scale faults are routine inputs, not exceptional shutdowns.
# The scheduler needs to know, for every exception an engine call
# raises, how much state it poisons — that is the whole containment
# contract:
#
# - REQUEST-scoped: one request's admission went wrong (malformed
#   prompt the model chokes on, a prefill error). The engine's abort
#   guards already reclaimed the slot/pages, device state for everyone
#   else is coherent — fail THAT request with its cause, keep serving.
# - ENGINE-scoped: device state is suspect (an XLA/device error inside
#   a decode segment that mutates every slot's cache). The engine must
#   be rebuilt (`reset_state`) and in-flight requests replayed.
# - FATAL: process-level signals (KeyboardInterrupt/SystemExit) that
#   must never be swallowed by a recovery loop.

class RequestFault(RuntimeError):
    """A fault scoped to ONE request: fail that request with its cause
    and keep serving everyone else (the engine's device state is
    coherent — admission abort guards reclaimed any claimed capacity).
    Raise this from model/engine code running single-request work (the
    admission/prefill/chunk seams, where the scheduler knows which
    request is in flight). At a BATCH-wide seam (a decode segment over
    every slot) there is no single request to attribute it to, so a
    supervisor must still treat it as engine-scoped there."""


class EngineFault(RuntimeError):
    """A fault that poisons the ENGINE's device state (e.g. a device
    error mid decode segment): the supervisor must rebuild state
    (:meth:`ContinuousBatchingEngine.reset_state`) and replay in-flight
    requests from their stored prompt + tokens emitted so far."""


# seams where an unclassified exception defaults to request scope: the
# engine was doing single-request work behind an abort guard, so shared
# device state was never touched
REQUEST_SITES = frozenset({"admit", "prefill", "chunk"})

# paged-engine admission policies (see PagedContinuousBatchingEngine)
ADMISSION_MODES = ("reserved", "optimistic")

# speculative-decoding execution modes (see ContinuousBatchingEngine):
# "host" proposes on host with a device→host readback per verify step;
# "device" fuses propose→verify→accept into one compiled segment loop
# (the history ring IS the draft source — one readback per segment)
SPEC_MODES = ("host", "device")

# device-mode draft sources: "ngram" = suffix-match lookup over the
# slot's device history ring (ngram.propose_device, the host proposer's
# windowed twin); "self" = reuse the verify forward's trailing greedy
# tokens as the NEXT step's drafts (EAGLE-lite, no trained heads — the
# ring still bootstraps each segment's first step)
SPEC_DRAFTS = ("ngram", "self")


class PagePoolExhausted(RuntimeError):
    """Optimistic-mode page growth could not be satisfied in the
    inter-segment gap even for the requests the caller chose to keep.

    ``rids`` names the requests whose next-segment growth the pool
    cannot cover. A serving scheduler never lets this surface — it
    preempts victims in the gap until growth fits (or fails a request
    that cannot fit even alone, with this as the typed cause); a bare
    engine driver that ignores memory pressure sees it loudly from
    ``decode_segment`` instead of silently corrupting KV."""

    def __init__(self, rids, message: str):
        super().__init__(message)
        self.rids = list(rids)


def classify_fault(exc: BaseException, site: str = "decode") -> str:
    """Blast radius of ``exc`` raised at serving seam ``site``:
    ``"request"`` / ``"engine"`` / ``"fatal"``.

    Explicit :class:`RequestFault` / :class:`EngineFault` win over the
    site default; anything unclassified is request-scoped at the
    single-request seams (:data:`REQUEST_SITES` — admission work runs
    behind abort guards that reclaim capacity) and engine-scoped at the
    batch-wide ones (``decode``, ``collect``). Caveat for supervisors:
    a ``"request"`` verdict is only ACTIONABLE where a single request
    is in flight — at a batch-wide seam there is nobody to pin it on,
    so the serving scheduler escalates any non-fatal fault there to
    engine recovery regardless of this verdict."""
    if isinstance(exc, (KeyboardInterrupt, SystemExit)):
        return "fatal"
    if isinstance(exc, EngineFault):
        return "engine"
    if isinstance(exc, RequestFault):
        return "request"
    return "request" if site in REQUEST_SITES else "engine"


def prefill_buckets_for(spec, max_len: int, floor: int = 16):
    """Normalize a ``prefill_buckets`` engine knob to a sorted tuple of
    pad targets, or None (bucketing disabled — exact-length prefill, one
    compiled program per distinct prompt length).

    ``"auto"`` (the engines' default) gives powers of two from ``floor``
    up to ``max_len`` — O(log max_len) prefill programs instead of
    O(#distinct prompt lengths); an explicit sequence is deduped/sorted
    and always extended to cover ``max_len`` (every admissible prompt
    must land in SOME bucket)."""
    if spec is None:
        return None
    if spec == "auto":
        out = []
        b = int(floor)
        if b < 1:
            raise ValueError(f"bucket floor must be >= 1, got {floor}")
        while b < max_len:
            out.append(b)
            b *= 2
        out.append(max_len)
        return tuple(out)
    out = sorted({int(b) for b in spec})
    if not out or out[0] < 1:
        raise ValueError(f"prefill_buckets must be positive ints, got "
                         f"{spec!r}")
    if out[-1] > max_len:
        raise ValueError(
            f"prefill bucket {out[-1]} exceeds max_len={max_len}")
    if out[-1] < max_len:
        out.append(max_len)
    return tuple(out)


def _normalize_prefill_chunk(prefill_chunk, max_len: int):
    """Validate the ``prefill_chunk`` engine knob (shared by all
    engines). ``max_len`` must be a multiple of the chunk: chunks start
    at multiples of C, so divisibility is exactly what guarantees every
    (padded) chunk window [pos, pos+C) stays inside the cache — an
    overhanging final chunk would be CLAMPED by dynamic_update_slice
    and silently overwrite earlier prompt KV."""
    if prefill_chunk is None:
        return None
    if isinstance(prefill_chunk, bool) or not isinstance(
            prefill_chunk, (int, np.integer)) or prefill_chunk < 1:
        raise ValueError(
            f"prefill_chunk must be a positive int or None, got "
            f"{prefill_chunk!r}")
    if max_len % int(prefill_chunk) != 0:
        raise ValueError(
            f"max_len({max_len}) must be a multiple of "
            f"prefill_chunk({int(prefill_chunk)}) — a final chunk "
            "overhanging the cache would clamp and corrupt earlier KV")
    return int(prefill_chunk)


def _bucket_for(buckets, plen: int) -> int:
    """Smallest bucket >= plen (buckets sorted, last == max_len)."""
    for b in buckets:
        if b >= plen:
            return b
    return buckets[-1]


def _pad_ids(ids: np.ndarray, width: int) -> np.ndarray:
    """Right-pad [B, plen] token ids to [B, width] (pad id 0). Padded
    prefill is numerically identical to exact prefill: causal masking
    means no REAL query position ever attends a pad key, the engines
    read logits at the true last position (not -1), and the garbage KV
    the pad tail writes past plen is masked by every decode read (all
    decode attention is length-masked) and overwritten as the sequence
    grows."""
    plen = ids.shape[1]
    if plen >= width:
        return ids
    return np.pad(ids, ((0, 0), (0, width - plen)))


class GenerationConfig:
    """Per-request decoding parameters.

    Validated at CONSTRUCTION: in online serving a config arrives from
    the network per request, and a malformed one must be rejected at
    admission (an HTTP 400), never crash a shared decode segment that
    other requests are riding in.
    """

    def __init__(self, max_new_tokens: int = 64, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0, do_sample: bool = False,
                 eos_token_id: Optional[int] = None, seed: int = 0,
                 speculative: bool = False,
                 draft_k: Optional[int] = None,
                 adapter: Optional[str] = None):
        INT32_MAX = 2 ** 31 - 1   # engine state is int32 on device; a
        #                           larger value must fail HERE, not
        #                           leak a slot mid-admission
        if (isinstance(max_new_tokens, bool)
                or not isinstance(max_new_tokens, (int, np.integer))
                or not 1 <= max_new_tokens <= INT32_MAX):
            raise ValueError(
                f"max_new_tokens must be an int in [1, 2**31), got "
                f"{max_new_tokens!r}")
        if not (isinstance(temperature, (int, float, np.floating))
                and temperature > 0):
            # `not (x > 0)` also rejects NaN
            raise ValueError(
                f"temperature must be > 0, got {temperature!r}")
        if (isinstance(top_k, bool)
                or not isinstance(top_k, (int, np.integer))
                or not 0 <= top_k <= INT32_MAX):
            raise ValueError(
                f"top_k must be an int in [0, 2**31) (0 disables), got "
                f"{top_k!r}")
        if not (isinstance(top_p, (int, float, np.floating))
                and 0 < top_p <= 1):
            raise ValueError(
                f"top_p must satisfy 0 < top_p <= 1, got {top_p!r}")
        if eos_token_id is not None and (
                isinstance(eos_token_id, bool)
                or not isinstance(eos_token_id, (int, np.integer))
                or not 0 <= eos_token_id <= INT32_MAX):
            raise ValueError(
                f"eos_token_id must be an int in [0, 2**31) or None, "
                f"got {eos_token_id!r}")
        if isinstance(seed, bool) or not isinstance(seed,
                                                   (int, np.integer)):
            raise ValueError(f"seed must be an int, got {seed!r}")
        if draft_k is not None and (
                isinstance(draft_k, bool)
                or not isinstance(draft_k, (int, np.integer))
                or not 1 <= draft_k <= 256):
            # 256 is far above any useful draft window; an absurd value
            # must fail at admission, not compile an absurd program
            raise ValueError(
                f"draft_k must be an int in [1, 256] or None "
                f"(engine default), got {draft_k!r}")
        if adapter is not None and (not isinstance(adapter, str)
                                    or not adapter
                                    or len(adapter) > 256):
            # a malformed adapter name must fail at config construction
            # (the HTTP 400 path), never inside a shared decode segment
            raise ValueError(
                f"adapter must be a non-empty str (<= 256 chars) or "
                f"None (base model), got {adapter!r}")
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.do_sample = bool(do_sample)
        self.eos_token_id = (None if eos_token_id is None
                             else int(eos_token_id))
        self.seed = int(seed)
        # speculative decoding opt-in (continuous-batching engines
        # built with draft_k > 0): greedy requests propose/verify
        # n-gram drafts per segment step; sampled requests fall back to
        # plain decode (lossless acceptance needs the argmax target).
        # draft_k caps THIS request's draft window (None = the
        # engine's).
        self.speculative = bool(speculative)
        self.draft_k = None if draft_k is None else int(draft_k)
        # multi-tenant LoRA: the fine-tune this request decodes under
        # (None = base model). Resolved to a bank index at admission —
        # an unknown/unloading name fails THAT request at the admit
        # seam (request-scoped), everyone else keeps serving.
        self.adapter = adapter


def _sample(logits, key, cfg: GenerationConfig):
    """One next-token choice from [B, V] logits."""
    if not cfg.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / max(cfg.temperature, 1e-6)
    if cfg.top_k and cfg.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -cfg.top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if cfg.top_p < 1.0:
        sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_l, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p; cutoff = last kept logit
        keep = cum - probs < cfg.top_p
        cutoff = jnp.min(jnp.where(keep, sorted_l, jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def _sample_rows(logits, key, samp):
    """Per-ROW next-token choice from [B, V] logits: every sampling
    parameter (greedy-vs-sample, temperature, top-k, top-p, eos) is a
    per-slot device VECTOR installed at admission, not a trace constant
    — so ONE compiled segment program serves any mix of per-request
    GenerationConfigs (the continuous-batching engines' online form;
    the old cfg-keyed specialization recompiled per distinct config).

    Greedy rows reduce to the exact argmax `_sample` computes, so mixed
    batches keep bitwise greedy parity with the dense engine. Rows with
    top_k == 0 / top_p == 1.0 skip those filters (same gating as
    `_sample`'s `if` branches, expressed as masks).

    Each row draws from its OWN noise stream: the request's seed (a
    per-slot vector) is folded into the shared per-step key, so a
    request's sampled trajectory depends on ITS GenerationConfig.seed,
    not on which other requests share the batch."""
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def drawn(_):
        scaled = (logits.astype(jnp.float32)
                  / jnp.maximum(samp["temp"], 1e-6)[:, None])
        desc = jnp.sort(scaled, axis=-1)[:, ::-1]
        k_eff = jnp.clip(samp["top_k"], 1, vocab)
        kth = jnp.take_along_axis(desc, (k_eff - 1)[:, None], axis=-1)
        scaled = jnp.where((samp["top_k"] > 0)[:, None] & (scaled < kth),
                           -jnp.inf, scaled)
        # top-p runs over the top-k-FILTERED logits (_sample's order)
        desc2 = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(desc2, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = cum - probs < samp["top_p"][:, None]
        cutoff = jnp.min(jnp.where(keep, desc2, jnp.inf), axis=-1,
                         keepdims=True)
        scaled = jnp.where(
            (samp["top_p"] < 1.0)[:, None] & (scaled < cutoff),
            -jnp.inf, scaled)
        keys = jax.vmap(lambda s: jax.random.fold_in(key, s))(
            samp["seed"])
        return jax.vmap(jax.random.categorical)(keys, scaled) \
            .astype(jnp.int32)

    # all-greedy batches (the do_sample=False default) skip the whole
    # sort/softmax/cumsum pipeline at RUNTIME — lax.cond on a traced
    # scalar executes one branch, so the single-program property holds
    # while a greedy segment pays only the argmax
    sampled = jax.lax.cond(jnp.any(samp["sample"]), drawn,
                           lambda _: greedy, None)
    return jnp.where(samp["sample"], sampled, greedy)


def _prompt_ids(prompt):
    """Normalize a prompt (Tensor / ndarray / list) to int32 [1, plen].
    serve()'s capacity probe and add_request MUST agree on this — a
    Tensor probed with a bare np.asarray becomes a size-1 object array
    and defeats the paged defer logic."""
    return np.asarray(prompt.value if isinstance(prompt, Tensor)
                      else prompt).astype(np.int32).reshape(1, -1)


def _prompt_len(prompt) -> int:
    return _prompt_ids(prompt).shape[1]


# back-compat alias: the n-gram machinery lives in inference/ngram.py
# now (shared by the offline generate_speculative path and the batched
# serving engines' per-slot proposers)
_NgramIndex = NgramIndex


class CausalLMEngine:
    """Compiled prefill + decode for a causal LM exposing
    ``init_cache`` / ``forward_with_cache`` (LlamaForCausalLM, GPT...).

    Usage::

        eng = CausalLMEngine(model, max_batch=8, max_len=2048)
        out_ids = eng.generate(prompt_ids, GenerationConfig(max_new_tokens=64))
    """

    def __init__(self, model, max_batch: int, max_len: int,
                 prefill_buckets="auto",
                 prefill_chunk: Optional[int] = None):
        self.model = model
        self.max_batch = max_batch
        self.max_len = max_len
        self.prefill_buckets = prefill_buckets_for(prefill_buckets,
                                                   max_len)
        self.prefill_chunk = _normalize_prefill_chunk(prefill_chunk,
                                                      max_len)
        self.params = {k: p.value for k, p in model.named_parameters()}

        def prefill(params, ids, caches, last_idx):
            logits, caches = self._fwd(params, ids, caches, 0)
            return logits[:, last_idx], caches

        # jax.jit's own cache specializes per ids shape — with bucketing
        # the prompt is padded to one of O(log max_len) widths, so the
        # compiled prefill program count is bounded by len(buckets)
        # instead of #distinct prompt lengths. last_idx (the true last
        # prompt position) is a traced value, not a shape. decode stays
        # keyed by GenerationConfig because the config is *trace-static*
        # (branching on do_sample/eos), not shape-derived.
        self._prefill = monitor.monitored_jit(prefill, name="lm_prefill",
                                              donate_argnums=(2,))

        def prefill_chunk_fn(params, ids, caches, pos, last_idx):
            # pos is TRACED: one compiled program serves every chunk of
            # every prompt (llama routes traced-offset prefill through
            # ops.pallas.prefix_chunk_attention)
            logits, caches = self._fwd(params, ids, caches, pos)
            return logits[:, last_idx], caches

        self._prefill_chunk = monitor.monitored_jit(
            prefill_chunk_fn, name="lm_prefill_chunk", donate_argnums=(2,))
        self._decode_cache = {}

    # -- pure functions -------------------------------------------------------
    def _fwd(self, params, ids, caches, pos):
        from ..core.autograd import no_grad

        with substituted_state(self.model, params), no_grad():
            logits, caches = self.model.forward_with_cache(
                Tensor(ids), caches, pos)
        return (logits.value if isinstance(logits, Tensor) else logits,
                caches)

    def _run_prefill(self, ids: np.ndarray, caches):
        """Bounded-compile prefill dispatch: chunked for prompts longer
        than ``prefill_chunk`` (fixed-shape chunks at traced offsets —
        ONE compiled program reused for every chunk), else padded up to
        the covering bucket. Returns (last-position logits [B, V],
        caches)."""
        plen = ids.shape[1]
        C = self.prefill_chunk
        if C is not None and plen > C:
            pos = 0
            while pos < plen:
                chunk = ids[:, pos:pos + C]
                r = chunk.shape[1]
                if r < C:       # only the FINAL chunk may be partial
                    chunk = _pad_ids(chunk, C)
                last_logits, caches = self._prefill_chunk(
                    self.params, chunk, caches, jnp.int32(pos),
                    jnp.int32(r - 1))
                pos += C
            return last_logits, caches
        width = (plen if self.prefill_buckets is None
                 else _bucket_for(self.prefill_buckets, plen))
        return self._prefill(self.params, _pad_ids(ids, width), caches,
                             jnp.int32(plen - 1))

    def _decode_fn(self, n_steps: int, cfg: GenerationConfig):
        key_cfg = (n_steps, cfg.do_sample, cfg.temperature, cfg.top_k,
                   cfg.top_p, cfg.eos_token_id)
        if key_cfg not in self._decode_cache:
            def decode_n(params, first_tok, caches, pos0, key):
                # a row whose FIRST sampled token is already EOS must stay
                # frozen through the scan
                if cfg.eos_token_id is not None:
                    done_init = first_tok == cfg.eos_token_id
                else:
                    done_init = jnp.zeros(first_tok.shape, bool)

                def step(carry, _):
                    tok, caches, pos, key, done = carry
                    logits, caches = self._fwd(params, tok[:, None],
                                               caches, pos)
                    key, sub = jax.random.split(key)
                    nxt = _sample(logits[:, 0], sub, cfg)
                    if cfg.eos_token_id is not None:
                        nxt = jnp.where(done, cfg.eos_token_id, nxt)
                        done = done | (nxt == cfg.eos_token_id)
                    return (nxt, caches, pos + 1, key, done), nxt

                (_, caches, _, _, _), toks = jax.lax.scan(
                    step, (first_tok, caches, pos0, key, done_init), None,
                    length=n_steps)
                return jnp.swapaxes(toks, 0, 1), caches   # [B, n_steps]

            self._decode_cache[key_cfg] = monitor.monitored_jit(
                decode_n, name="lm_decode", donate_argnums=(2,))
        return self._decode_cache[key_cfg]

    # -- public ---------------------------------------------------------------
    def generate(self, input_ids, config: Optional[GenerationConfig] = None):
        """input_ids: [B, prompt_len] (np/jnp/Tensor). Returns np.ndarray
        [B, prompt_len + max_new_tokens] (prompt + generated)."""
        cfg = config or GenerationConfig()
        ids = np.asarray(input_ids.value if isinstance(input_ids, Tensor)
                         else input_ids).astype(np.int32)
        b, plen = ids.shape
        if b > self.max_batch:
            raise ValueError(
                f"batch {b} exceeds max_batch={self.max_batch} the engine "
                f"was built for")
        if plen + cfg.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt({plen}) + max_new_tokens({cfg.max_new_tokens}) "
                f"exceeds engine max_len({self.max_len})")
        caches = self.model.init_cache(b, self.max_len)
        last_logits, caches = self._run_prefill(ids, caches)
        key = jax.random.PRNGKey(cfg.seed)
        key, sub = jax.random.split(key)
        first = _sample(last_logits, sub, cfg)
        n_rest = cfg.max_new_tokens - 1
        if n_rest > 0:
            rest, caches = self._decode_fn(n_rest, cfg)(
                self.params, first, caches, jnp.int32(plen), key)
            gen = np.concatenate([np.asarray(first)[:, None],
                                  np.asarray(rest)], axis=1)
        else:
            gen = np.asarray(first)[:, None]
        return np.concatenate([ids, gen], axis=1)

    # -- speculative decoding -------------------------------------------------
    def _spec_verify_fn(self, width: int):
        """One jitted verification forward of ``width`` tokens at a
        traced offset (compiled once per width)."""
        key = ("spec", width)
        if key not in self._decode_cache:
            def verify(params, inp, caches, pos):
                return self._fwd(params, inp, caches, pos)

            self._decode_cache[key] = monitor.monitored_jit(
                verify, name="lm_spec_verify", donate_argnums=(2,))
        return self._decode_cache[key]

    def generate_speculative(self, input_ids,
                             config: Optional[GenerationConfig] = None,
                             draft_k: int = 8, ngram_max: int = 3):
        """LOSSLESS n-gram (prompt-lookup) speculative decoding: propose
        ``draft_k`` tokens by continuing the longest recent-suffix match
        found earlier in the context, verify ALL of them in ONE model
        forward, and accept the matched prefix plus the model's own next
        token — so each forward yields between 1 and draft_k+1 tokens.

        Losslessness: every emitted token is the model's own argmax —
        acceptance targets and the bonus token come FROM the
        verification forward, so the output is the model's greedy
        continuation by construction. Bitwise it equals ``generate()``
        wherever the chunked-verify and one-token decode attention paths
        reduce identically (exactly true in f32 / the test suite; on a
        bf16 TPU cache the two kernels' reduction orders can low-bit
        flip a near-tied argmax — same class of divergence as any
        speculative-vs-sequential system).

        Greedy-only and B=1 (the latency-serving case). The reference
        has no speculative path; on TPU, decode is HBM-bandwidth-bound,
        so verifying k+1 positions costs barely more than one — the win
        is model forwards per token (reported in
        ``self.last_spec_stats``). Rejected drafts leave stale cache
        entries past the accepted length; the next verification
        overwrites them, and the cached-attention mask (absolute
        ``kv_pos <= sq_pos``) never reads beyond the query's position.
        """
        cfg = config or GenerationConfig()
        if cfg.do_sample:
            raise ValueError(
                "speculative decoding here is greedy-only (lossless "
                "acceptance needs the argmax target); use generate() "
                "for sampling")
        ids = np.asarray(input_ids.value if isinstance(input_ids, Tensor)
                         else input_ids).astype(np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        b, plen = ids.shape
        if b != 1:
            # NOT _prompt_ids: its reshape(1, -1) would silently flatten
            # a batch into one long prompt
            raise ValueError("speculative decoding serves B=1 requests")
        if plen + cfg.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt({plen}) + max_new_tokens({cfg.max_new_tokens}) "
                f"exceeds engine max_len({self.max_len})")
        caches = self.model.init_cache(1, self.max_len)
        last_logits, caches = self._run_prefill(ids, caches)
        out = [int(np.argmax(np.asarray(last_logits[0])))]
        # per-sequence proposer state (inference/ngram.py): context =
        # prompt + every emitted token, extended incrementally — the
        # SAME unit the batched serving engines keep per slot
        prop = NgramProposer([int(t) for t in ids[0]] + [out[0]],
                             draft_k, ngram_max)
        pos = plen                      # tokens the CACHE holds
        forwards = 1                    # the prefill
        extra = 0                       # emitted tokens beyond 1/forward
        eos = cfg.eos_token_id
        verify = self._spec_verify_fn(draft_k + 1)
        while (len(out) < cfg.max_new_tokens
               and (eos is None or out[-1] != eos)
               and pos + 1 + draft_k <= self.max_len):
            draft = prop.propose()
            inp = np.asarray([[out[-1]] + draft], np.int32)
            logits, caches = verify(self.params, inp, caches,
                                    jnp.int32(pos))
            forwards += 1
            greedy = np.asarray(jnp.argmax(logits[0], axis=-1))
            m = 0
            while m < draft_k and int(greedy[m]) == draft[m]:
                m += 1
            accepted = draft[:m] + [int(greedy[m])]
            before = len(out)
            for t in accepted:
                out.append(t)
                prop.extend([t])
                if (len(out) >= cfg.max_new_tokens
                        or (eos is not None and t == eos)):
                    break
            extra += len(out) - before - 1
            # cache gained [out_prev_last, accepted drafts]; the final
            # accepted token is the model's own pick, not yet cached
            pos += 1 + m
        # tail: plain 1-wide steps when max_len headroom < draft_k+1
        one = self._spec_verify_fn(1)
        while (len(out) < cfg.max_new_tokens
               and (eos is None or out[-1] != eos)
               and pos + 1 <= self.max_len - 1):
            logits, caches = one(self.params,
                                 np.asarray([[out[-1]]], np.int32),
                                 caches, jnp.int32(pos))
            forwards += 1
            out.append(int(np.argmax(np.asarray(logits[0, 0]))))
            prop.extend([out[-1]])
            pos += 1
        # generate() always emits the prefill token, even at budget 0
        budget = max(cfg.max_new_tokens, 1)
        if eos is not None and eos in out:
            # generate() freezes finished rows on eos — match exactly
            i = out.index(eos)
            out = out[:i + 1] + [eos] * (budget - i - 1)
        out = out[:budget]
        self.last_spec_stats = {"forwards": forwards,
                                "tokens": len(out),
                                # emitted draft/bonus tokens beyond the
                                # one-per-forward floor: with eos=None
                                # tokens == forwards + accepted exactly,
                                # so speedup bars can be DERIVED from
                                # the measured acceptance instead of
                                # hard-coding an environment-dependent
                                # tokens/forward threshold
                                "accepted_draft_tokens": extra,
                                "tokens_per_forward":
                                    len(out) / max(forwards, 1)}
        return np.concatenate([ids, np.asarray([out], np.int32)], axis=1)


class _ChunkedAdmission:
    """Host-side state of one in-flight CHUNKED admission. The slot (and,
    paged, the request's worst-case pages) is already claimed; ``mini``
    accumulates the prompt's KV chunk by chunk until the final chunk
    installs it and the request goes live under ``rid``. Drive with
    ``engine.admit_chunk``; reclaim with ``engine.abort_admit``."""

    __slots__ = ("rid", "slot", "ids", "plen", "cfg", "mini", "off",
                 "t0", "closed", "chunks_done", "last_logits")

    def __init__(self, rid, slot, ids, plen, cfg, mini, off=0):
        self.rid = rid
        self.slot = slot
        self.ids = ids
        self.plen = plen
        self.cfg = cfg
        self.mini = mini
        # chunk cursor; a prefix-cache hit starts it past the cached
        # coverage (aligned down to a chunk boundary) so cached chunks
        # never recompute
        self.off = off
        self.t0 = time.perf_counter()
        self.closed = False
        self.chunks_done = 0
        self.last_logits = None


class ContinuousBatchingEngine:
    """Ragged / continuous batching decode service.

    The dense :class:`CausalLMEngine` serves one common-length batch per
    ``generate()``. The reference's decode kernel instead removes padding
    and serves MIXED-length batches with per-sequence lengths
    (fused_multi_transformer_op.cu.h:1641 remove_padding, :1680 the
    length-indexed masked MHA). This engine is the TPU-native equivalent:

    - a fixed pool of ``max_batch`` cache SLOTS, each with its own
      ``seq_len`` (the decode_mha kernel's per-row ``seq_lens`` vector —
      its S-block grid skips blocks past each row's length, so a short
      row costs O(its length), not O(max_len));
    - requests are ADMITTED into free slots between jitted decode
      segments (prefill is per-request B=1, its rows scattered into the
      pool), and finished rows are retired between segments — new work
      starts without waiting for the longest running request;
    - one compiled segment program serves every slot occupancy pattern
      AND every mix of per-request GenerationConfigs (slot ids, lengths
      and sampling parameters are traced values, not shapes or trace
      constants — see ``_sample_rows``);
    - prefill compiles are BOUNDED: prompts pad to ``prefill_buckets``
      (default powers of two — len(buckets) compiled prefill programs,
      not one per distinct prompt length, all pre-compilable via
      :meth:`warmup`), and prompts longer than ``prefill_chunk`` can
      admit chunk-by-chunk across inter-segment gaps
      (:meth:`begin_admit` / :meth:`admit_chunk`) so one long prompt
      never monopolizes the gap. Both are numerically exact — see
      PERF.md "Prefill cost".

    Usage::

        eng = ContinuousBatchingEngine(model, max_batch=4, max_len=512)
        outs = eng.serve([ids1, ids2, ...], GenerationConfig(...))
    """

    def __init__(self, model, max_batch: int, max_len: int,
                 prefill_buckets="auto",
                 prefill_chunk: Optional[int] = None,
                 draft_k: int = 0, ngram_max: int = 3,
                 spec_mode: str = "host", spec_draft: str = "ngram",
                 spec_history: int = 128,
                 lora_capacity: int = 0, lora_rank: int = 8,
                 lora_targets=("q", "k", "v", "o"),
                 tp_degree: int = 1, tp_devices=None):
        from .tp import (TP_AXIS, make_tp_mesh, shard_params_tp,
                         validate_tp_model)

        if (isinstance(draft_k, bool)
                or not isinstance(draft_k, (int, np.integer))
                or not 0 <= draft_k <= 256):
            raise ValueError(
                f"draft_k must be an int in [0, 256] (0 disables "
                f"speculative decoding), got {draft_k!r}")
        if spec_mode not in SPEC_MODES:
            raise ValueError(
                f"spec_mode must be one of {SPEC_MODES}, got "
                f"{spec_mode!r}")
        if spec_draft not in SPEC_DRAFTS:
            raise ValueError(
                f"spec_draft must be one of {SPEC_DRAFTS}, got "
                f"{spec_draft!r}")
        if (isinstance(spec_history, bool)
                or not isinstance(spec_history, (int, np.integer))
                or not 8 <= spec_history <= 65536):
            raise ValueError(
                f"spec_history must be an int in [8, 65536] (the "
                f"device history-ring width), got {spec_history!r}")
        if (isinstance(lora_capacity, bool)
                or not isinstance(lora_capacity, (int, np.integer))
                or lora_capacity < 0):
            raise ValueError(
                f"lora_capacity must be an int >= 0 (0 disables "
                f"multi-tenant LoRA), got {lora_capacity!r}")
        # tensor parallelism (inference/tp.py): tp_degree > 1 builds a
        # 1-D "mp" mesh and shards weights (per their layer pspecs) and
        # every KV store on the (kv_)head axis; per-slot vectors, page
        # tables, and all host bookkeeping REPLICATE, so the engine's
        # programs keep their one-program-per-shape invariant at any
        # degree. tp_devices pins the mesh to a device subset (the
        # ReplicaSpec fleet-partitioning seam). Must be resolved before
        # _init_decode_state builds the device pools.
        # mesh first (validates the degree and device availability),
        # then the model-geometry divisibility check
        self.tp_mesh = make_tp_mesh(tp_degree, tp_devices)
        validate_tp_model(model, tp_degree)
        self.tp_degree = int(tp_degree)
        # the (mesh, axis) handle the model forwards thread into the
        # attention ops' shard_map wrap (None = pre-TP trace, bitwise
        # the single-device engine)
        self._tp = (None if self.tp_mesh is None
                    else (self.tp_mesh, TP_AXIS))
        self.model = model
        self.max_batch = max_batch
        self.max_len = max_len
        self.prefill_buckets = prefill_buckets_for(prefill_buckets,
                                                   max_len)
        self.prefill_chunk = _normalize_prefill_chunk(prefill_chunk,
                                                      max_len)
        # speculative decoding (per-slot capability): draft_k > 0
        # widens the decode path with ONE extra compiled program (the
        # (draft_k+1)-token verify step) that spec-opted slots ride;
        # plain/sampled slots share it at 1 token/step. 0 = the spec
        # path never compiles and decode_segment is exactly the plain
        # scan.
        self.draft_k = int(draft_k)
        self.ngram_max = int(ngram_max)
        # speculative execution mode + device-draft source (idle-only
        # attributes, like draft_k — the serving Server mirrors them):
        # "device" replaces the host per-verify-step loop with ONE
        # fused compiled segment whose draft source is the per-slot
        # history ring below
        self.spec_mode = spec_mode
        self.spec_draft = spec_draft
        self.spec_history = int(spec_history)
        self._spec = {}                # rid -> NgramProposer (spec rows)
        # engine-lifetime host accounting (serve_bench / spec_stats):
        # proposed/accepted draft tokens, verify forwards, per-slot
        # participations (slot_steps), tokens emitted (spec segments
        # only), blocking per-verify-step host readbacks (host mode's
        # documented price; structurally 0 in device mode)
        self._spec_totals = {"proposed": 0, "accepted": 0,
                             "forwards": 0, "slot_steps": 0,
                             "emitted": 0, "host_syncs": 0}
        # engine label: concurrent engines (multi-model serving) publish
        # throughput side by side; retired via close()/__del__
        self._monitor_engine = monitor.instance_label("engine")
        self.params = {k: p.value for k, p in model.named_parameters()}
        if self.tp_mesh is not None:
            # column-parallel q/k/v/gate/up, row-parallel o/down,
            # vocab-parallel embed/lm_head — straight from the layer
            # pspec annotations the training stack already carries
            self.params = shard_params_tp(model, self.params,
                                          self.tp_mesh)
        # multi-tenant LoRA (lora_capacity > 0): an AdapterRegistry owns
        # the stacked per-target factor bank ([L, K+1, r, d] per
        # projection, index 0 = base model) plus hot load/unload; every
        # serving program takes the bank as a jit ARGUMENT and gathers
        # each slot's delta by its per-slot adapter_idx device vector —
        # one compiled program serves any adapter mix, loads rewrite
        # bank rows only (zero per-adapter compiles). 0 disables: the
        # programs take an empty-dict bank and trace the exact
        # pre-LoRA computation.
        self.lora_capacity = int(lora_capacity)
        self.adapters = None
        if self.lora_capacity:
            shapes_fn = getattr(model, "lora_shapes", None)
            if shapes_fn is None:
                raise ValueError(
                    f"lora_capacity needs a model exposing "
                    f"lora_shapes(targets) (llama does); "
                    f"{type(model).__name__} does not")
            num_layers, shapes = shapes_fn(tuple(lora_targets))
            # lazy import: paddle_tpu.serving imports this module
            from ..serving.adapters import AdapterRegistry

            dtype = next(iter(self.params.values())).dtype
            self.adapters = AdapterRegistry(
                self.lora_capacity, lora_rank, tuple(lora_targets),
                num_layers, shapes, dtype, self._monitor_engine)
        # adapter-index bookkeeping around admissions: slot -> index
        # while an admission is in flight (popped by _register /
        # _abort_admit), rid -> index while the request lives (released
        # by _retire). guarded-by: scheduler-thread
        self._aidx_stash = {}
        self._rid_aidx = {}
        self._init_decode_state()
        self._slot_req = {}            # slot -> request id
        self._tokens = {}              # request id -> [generated ids]
        self._budget = {}              # request id -> remaining tokens
        self._cfg = {}                 # request id -> GenerationConfig
        self._finished = {}            # request id -> np.ndarray
        self._next_req = 0
        self._segments_run = 0         # PRNG stream position for sampling

        def prefill_one(params, ids, mini, last_idx, bank, aidx):
            # last_idx (the true last prompt position of a BUCKET-padded
            # prompt) is traced: compiled programs are keyed per bucket
            # width, not per prompt length. bank/aidx are the LoRA
            # inputs (aidx traced — one program serves every adapter;
            # an empty bank is trace-static and falls back to the
            # exact pre-LoRA prefill)
            lora = ((bank, jnp.full((ids.shape[0],), aidx, jnp.int32))
                    if bank else None)
            logits, mini = self._fwd_prefill(params, ids, mini,
                                             lora=lora)
            return logits[:, last_idx], mini

        self._prefill = monitor.monitored_jit(
            prefill_one, name="cb_prefill",
            owner=self._monitor_engine, donate_argnums=(2,))

        def prefill_chunk_fn(params, ids, mini, pos, last_idx, bank,
                             aidx):
            # traced offset -> ops.pallas.prefix_chunk_attention: ONE
            # compiled program serves every chunk of every admission
            lora = ((bank, jnp.full((ids.shape[0],), aidx, jnp.int32))
                    if bank else None)
            logits, mini = self._fwd_prefill(params, ids, mini, pos,
                                             lora=lora)
            return logits[:, last_idx], mini

        self._prefill_chunk = monitor.monitored_jit(
            prefill_chunk_fn, name="cb_prefill_chunk",
            owner=self._monitor_engine, donate_argnums=(2,))

        def admit(caches, mini, slot):
            return jax.tree.map(
                lambda c, m: jax.lax.dynamic_update_slice_in_dim(
                    c, m.astype(c.dtype), slot, axis=0), caches, mini)

        # mini is NOT donated: its rows are dtype-cast into the pool, so
        # the buffers can't alias (donation would only warn)
        self._admit = monitor.monitored_jit(admit, name="cb_admit",
                                            owner=self._monitor_engine,
                                            donate_argnums=(0,))

        H = self.spec_history

        def admit_state(lens, last, done, active, samp, hist, hl, slot,
                        plen, first, tok_done, temp, top_k, top_p,
                        do_samp, eos, seed, spec_k, adapter, hrow,
                        hlen):
            # one program for the per-slot scalars AND the request's
            # sampling parameters — admission sits in the
            # latency-critical gap between decode segments, and separate
            # .at[].set dispatches would each cost a host→device
            # round-trip where this costs one
            samp = {
                "temp": samp["temp"].at[slot].set(temp),
                "top_k": samp["top_k"].at[slot].set(top_k),
                "top_p": samp["top_p"].at[slot].set(top_p),
                "sample": samp["sample"].at[slot].set(do_samp),
                "eos": samp["eos"].at[slot].set(eos),
                "seed": samp["seed"].at[slot].set(seed),
                "spec_k": samp["spec_k"].at[slot].set(spec_k),
                "adapter": samp["adapter"].at[slot].set(adapter),
            }
            # history-ring seed: hrow is the prompt's last H-1 tokens
            # (host-padded to the fixed [H] shape — never a recompile);
            # the admission's FIRST token is a device scalar, so it
            # lands in its slot here rather than forcing a host sync
            hrow = jnp.where(
                hlen > 0,
                hrow.at[jnp.clip(hlen - 1, 0, H - 1)].set(first),
                hrow)
            return (lens.at[slot].set(plen),
                    last.at[slot].set(first),
                    done.at[slot].set(tok_done),
                    active.at[slot].set(True), samp,
                    hist.at[slot].set(hrow), hl.at[slot].set(hlen))

        self._admit_state = monitor.monitored_jit(
            admit_state, name="cb_admit_state",
            owner=self._monitor_engine,
            donate_argnums=(0, 1, 2, 3, 4, 5, 6))
        self._segment_cache = {}

    def _init_decode_state(self) -> None:
        """Fresh device-side decode state: caches, per-slot scalars,
        the per-slot SAMPLING vectors (see ``_sample_rows`` — each
        request's GenerationConfig is installed into its slot at
        admission, so one segment program serves mixed configs; eos -1
        means none), and the free-slot heap. ONE definition shared by
        ``__init__`` and ``reset_state`` — a supervised restart must
        rebuild exactly what construction builds, so a new per-slot
        vector added here can never be forgotten on the recovery
        path."""
        mb = self.max_batch
        self.caches = self._make_caches()
        self.lens = jnp.zeros((mb,), jnp.int32)
        self.last = jnp.zeros((mb,), jnp.int32)
        self.done_dev = jnp.zeros((mb,), bool)
        self.active_dev = jnp.zeros((mb,), bool)
        self.samp = {
            "temp": jnp.ones((mb,), jnp.float32),
            "top_k": jnp.zeros((mb,), jnp.int32),
            "top_p": jnp.ones((mb,), jnp.float32),
            "sample": jnp.zeros((mb,), bool),
            "eos": jnp.full((mb,), -1, jnp.int32),
            "seed": jnp.zeros((mb,), jnp.int32),
            # per-slot draft window (0 = plain decode): the widened
            # verify step caps each row's acceptance at ITS spec_k, so
            # one compiled program serves any spec/plain/sampled mix
            "spec_k": jnp.zeros((mb,), jnp.int32),
            # per-slot LoRA adapter index (0 = base model — bank row 0
            # is zeros, so the gathered delta is exactly 0.0): the
            # weights half of the per-slot-vector invariant. Rides the
            # samp dict so every program that takes the sampling
            # vectors sees it without a signature fork; consumed only
            # when a non-empty bank is passed alongside.
            "adapter": jnp.zeros((mb,), jnp.int32),
        }
        # per-slot token-history ring (device-mode speculative draft
        # source): each row holds the LAST spec_history tokens of
        # prompt + everything emitted, left-aligned, hist_len valid.
        # Installed at admission (_admit_state seeds prompt tail +
        # first token — a replayed request re-admits prompt+generated,
        # so the ring rebuilds exactly like the host proposer's
        # context), appended inside the fused segment. Allocated
        # unconditionally (mb x H int32 is trivial) so flipping
        # draft_k/spec_mode on an idle engine never needs a state
        # rebuild.
        self.hist = jnp.zeros((mb, self.spec_history), jnp.int32)
        self.hist_len = jnp.zeros((mb,), jnp.int32)
        if self.tp_mesh is not None:
            # the per-slot vectors REPLICATE on the mesh (the PR 2
            # invariant is TP-invariant): committing them here keeps
            # every program's input shardings identical from warmup
            # through serving — no sharding-keyed recompiles
            self.lens = self._tp_rep(self.lens)
            self.last = self._tp_rep(self.last)
            self.done_dev = self._tp_rep(self.done_dev)
            self.active_dev = self._tp_rep(self.active_dev)
            self.samp = {k: self._tp_rep(v)
                         for k, v in self.samp.items()}
            self.hist = self._tp_rep(self.hist)
            self.hist_len = self._tp_rep(self.hist_len)
        self._free = list(range(mb))

    # -- tensor-parallel placement helpers -----------------------------------
    def _tp_rep(self, x):
        """Commit a device value fully replicated on the TP mesh
        (identity when tp_degree == 1)."""
        if self.tp_mesh is None:
            return x
        from .tp import tp_replicate

        return tp_replicate(x, self.tp_mesh)

    def _tp_kv(self, caches):
        """Shard a per-layer KV list (slabs / pools / minis) on the
        kv-head axis (identity when tp_degree == 1)."""
        if self.tp_mesh is None:
            return caches
        from .tp import tp_shard_kv

        return tp_shard_kv(caches, self.tp_mesh)

    def _mini_cache(self, width: int):
        """One admission's B=1 dense mini cache, TP-placed: the mini is
        where prefill writes the prompt's KV before it installs into
        the pool, so it shards on the head axis exactly like the pool
        it feeds — the gather/scatter install programs then move
        head-local rows with zero cross-chip traffic."""
        return self._tp_kv(self.model.init_cache(1, width))

    def _make_caches(self):
        """Cache layout hook — the paged subclass replaces the dense
        [max_batch, max_len] slabs with page pools."""
        return self._tp_kv(
            self.model.init_cache(self.max_batch, self.max_len))

    def _bank(self) -> dict:
        """The LoRA factor bank to pass into the jitted serving
        programs: the registry's live arrays (a load/unload swaps them
        — same shapes, new data, no recompile), or ``{}`` when LoRA is
        disabled (trace-static: the programs fall back to the exact
        pre-LoRA computation)."""
        return self.adapters.bank if self.adapters is not None else {}

    def _fwd_kwargs(self, lora) -> dict:
        """Optional kwargs for the model's serving forwards: ``lora``
        only when batched adapters ride along, ``tp`` only when the
        engine runs on a mesh — so a model without either kwarg keeps
        working and the pre-TP/pre-LoRA traces stay byte-identical."""
        kw = {}
        if lora is not None:
            kw["lora"] = lora
        if self._tp is not None:
            kw["tp"] = self._tp
        return kw

    def _fwd_prefill(self, params, ids, caches, pos=0, lora=None):
        from ..core.autograd import no_grad

        with substituted_state(self.model, params), no_grad():
            logits, caches = self.model.forward_with_cache(
                Tensor(ids), caches, pos, **self._fwd_kwargs(lora))
        return (logits.value if isinstance(logits, Tensor) else logits,
                caches)

    def _fwd_ragged(self, params, tok, caches, lens, live, lora=None):
        from ..core.autograd import no_grad

        with substituted_state(self.model, params), no_grad():
            logits, caches = self.model.forward_decode_ragged(
                Tensor(tok), caches, lens, live,
                **self._fwd_kwargs(lora))
        return (logits.value if isinstance(logits, Tensor) else logits,
                caches)

    # -- admission / retirement (host-side, between segments) ---------------
    def _can_admit(self, prompt_len: int, cfg) -> bool:
        """Whether the head-of-queue request fits RIGHT NOW (a free slot
        is assumed). The paged subclass adds page-pool capacity; serve()
        consults this so a transiently full pool defers admission to the
        next inter-segment gap instead of raising mid-loop."""
        return True

    def free_slots(self) -> int:
        """Number of free cache slots right now. Public capacity probe
        (with :meth:`can_admit`) for serving schedulers — callers must
        not reach into the private ``_free`` list."""
        return len(self._free)

    def load(self) -> dict:  # lint: hot-path
        """Host-side load snapshot: ``{"free_slots", "active_slots",
        "max_batch"}`` plus, paged, ``{"free_pages", "total_pages",
        "occupancy"}``. Everything is host bookkeeping already
        maintained between segments — NO device sync, no HTTP, no lock
        beyond what the ints themselves need — so a health endpoint or
        a replica router can read it at any time, including while the
        scheduler thread is deep inside a decode segment. Consumed by
        ``Server.load()``/``/healthz`` and the router's least-loaded
        replica selection."""
        out = {"free_slots": len(self._free),
               "active_slots": len(self._slot_req),
               "max_batch": self.max_batch,
               "max_len": self.max_len,
               "tp_degree": self.tp_degree}
        if self.tp_mesh is not None:
            # mesh-shape surface for /healthz + routers: host-side
            # metadata only (the Mesh object is static), no device sync
            out["tp"] = {
                "degree": self.tp_degree,
                "axis": self.tp_mesh.axis_names[0],
                "devices": [str(d)
                            for d in self.tp_mesh.devices.flat]}
        alloc = getattr(self, "alloc", None)
        if alloc is not None:
            out["free_pages"] = alloc.free_pages
            out["total_pages"] = alloc.num_pages
            out["occupancy"] = round(alloc.occupancy, 4)
        if self.adapters is not None:
            # registry snapshot (resident/draining names, capacity) —
            # host dict reads only; the router's adapter-affinity
            # scoring and /healthz both consume it
            out["lora"] = self.adapters.resident()
        return out

    def can_admit(self, prompt_len: int, cfg: GenerationConfig) -> bool:
        """Non-raising admission probe: True iff ``add_request`` with a
        ``prompt_len``-token prompt and ``cfg`` would succeed RIGHT NOW
        (a free slot exists, the request fits ``max_len``, and — paged —
        the page pool can reserve its worst case).

        Contract: schedulers consult THIS and treat False as "defer to
        the next inter-segment gap" (or reject with backpressure);
        ``add_request`` raising is the programmer-error path for callers
        that skipped the probe, not a control-flow signal."""
        return (bool(self._free)
                and prompt_len + cfg.max_new_tokens <= self.max_len
                and self._can_admit(prompt_len, cfg))

    def add_request(self, prompt_ids, cfg: GenerationConfig) -> int:
        """Prefill one request into a free slot; returns the request id.
        Raises if no slot is free (call decode_segment / collect first)
        — probe :meth:`can_admit` to defer instead of catching."""
        if not self._free:
            raise RuntimeError("no free slot; drain with decode_segment()")
        t0 = time.perf_counter()
        ids = _prompt_ids(prompt_ids)
        plen = ids.shape[1]
        if plen + cfg.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt({plen}) + max_new_tokens({cfg.max_new_tokens}) "
                f"exceeds engine max_len({self.max_len})")
        if not self._can_admit(plen, cfg):
            raise RuntimeError(
                "page pool exhausted; drain with decode_segment()")
        aidx = self._acquire_adapter(cfg)
        slot = heapq.heappop(self._free)
        self._aidx_stash[slot] = aidx
        try:
            rid = self._next_req
            self._next_req += 1
            last_logits = self._admit_cache(slot, ids, plen, cfg)
            first, tok_done = self._sample_first(rid, last_logits, cfg)
            self._install_state(slot, plen, first, tok_done, cfg,
                                aidx=aidx, ids=ids)
        except BaseException:
            # a failed admission must not leak capacity: the popped
            # slot (and, paged, any page reservation _admit_cache made;
            # LoRA, the adapter reference) goes back to the pool before
            # the error propagates
            self._abort_admit(slot)
            raise
        self._init_spec(rid, ids, first, cfg)
        return self._register(slot, rid, first, tok_done, cfg, t0)

    def _acquire_adapter(self, cfg) -> int:
        """Resolve the request's adapter name to its bank index and
        take a live reference (0 = base model, no reference). Raises
        ValueError — a REQUEST-scoped verdict at the admission seam —
        for an unknown/unloading name or an adapter request against an
        engine built without ``lora_capacity``."""
        name = getattr(cfg, "adapter", None)
        if name is None:
            return 0
        if self.adapters is None:
            raise ValueError(
                f"request names adapter {name!r} but the engine was "
                f"built without lora_capacity")
        return self.adapters.acquire(name)

    def _adapter_salt(self, slot: int) -> bytes:
        """Prefix-cache chain salt for the admission in flight on
        ``slot`` (b"" = base namespace): cached KV is a function of the
        weights that produced it, so every adapter hashes its blocks in
        its own namespace and a cross-adapter warm hit is structurally
        impossible."""
        if self.adapters is None:
            return b""
        return self.adapters.salt(self._aidx_stash.get(slot, 0))

    def _init_spec(self, rid: int, ids, first, cfg) -> None:
        """Create the request's host-side n-gram proposer (speculative
        rows only), seeded with prompt + the admission's first token.
        A replayed/preempted request re-admits ``prompt + generated``
        as its prompt, so the proposer rebuilds with full context —
        the index is a pure function of it. Runs BEFORE ``_register``
        so an immediately-retired request's proposer is popped by
        ``_retire``, never leaked."""
        k = self._spec_k_for(cfg)
        if k > 0:
            self._spec[rid] = NgramProposer(
                [int(t) for t in ids[0]] + [int(first)], k,
                self.ngram_max)

    def _sample_first(self, rid: int, last_logits, cfg):
        """Sample the admission's first token from the prompt's
        last-position logits."""
        key = jax.random.PRNGKey(cfg.seed + rid)
        first = _sample(last_logits, key, cfg)[0]
        tok_done = (jnp.asarray(False) if cfg.eos_token_id is None
                    else first == cfg.eos_token_id)
        return first, tok_done

    def _spec_k_for(self, cfg) -> int:
        """Draft window for a request under ``cfg`` (0 = plain decode):
        needs an engine built with ``draft_k > 0``, a ``speculative``
        opt-in, and a GREEDY request — sampled rows fall back to plain
        decode (lossless acceptance needs the argmax target). The
        request's own ``draft_k`` caps the engine's (never widens it —
        the verify program's width is the engine's compile key)."""
        if (not self.draft_k or not getattr(cfg, "speculative", False)
                or cfg.do_sample):
            return 0
        k = getattr(cfg, "draft_k", None)
        return self.draft_k if k is None else min(int(k), self.draft_k)

    def _spec_k_of(self, rid: int) -> int:
        """Host-side draft window of an ACTIVE request (0 = plain)."""
        prop = self._spec.get(rid)
        return 0 if prop is None else prop.k

    def _install_state(self, slot: int, plen: int, first, tok_done,
                       cfg, aidx: int = 0, ids=None) -> None:
        """Install the request's per-slot scalars AND sampling parameters
        (the LoRA adapter index included) in ONE jitted program (shared
        by the dense and paged engines) instead of separate
        dispatches. ``ids`` (the host-side prompt, when the caller has
        one) seeds the slot's history ring with the prompt's trailing
        window — the device-mode draft source; a replayed request
        re-admits prompt+generated, so the ring rebuilds exactly like
        the host proposer's context."""
        eos = -1 if cfg.eos_token_id is None else cfg.eos_token_id
        H = self.spec_history
        hrow = np.zeros((H,), np.int32)
        hlen = 0
        if ids is not None:
            tail = np.asarray(ids, np.int32).reshape(-1)[-(H - 1):]
            hrow[:len(tail)] = tail
            hlen = len(tail) + 1     # + the first token (set in-program)
        (self.lens, self.last, self.done_dev, self.active_dev,
         self.samp, self.hist, self.hist_len) = self._admit_state(
            self.lens, self.last, self.done_dev, self.active_dev,
            self.samp, self.hist, self.hist_len, jnp.int32(slot),
            jnp.int32(plen), first,
            tok_done, jnp.float32(cfg.temperature),
            jnp.int32(cfg.top_k), jnp.float32(cfg.top_p),
            jnp.asarray(cfg.do_sample), jnp.int32(eos),
            jnp.int32(cfg.seed % (2 ** 31)),
            jnp.int32(self._spec_k_for(cfg)), jnp.int32(aidx),
            jnp.asarray(hrow), jnp.int32(hlen))

    def _register(self, slot: int, rid: int, first, tok_done, cfg,
                  t0: float) -> int:
        """Host-side bookkeeping tail of a completed admission (one-shot
        or chunked): record the request, retire degenerate ones, count
        metrics. Runs OUTSIDE the abort guard — no device call left."""
        # the admission's adapter reference transfers from the slot
        # stash to the live request; _retire releases it
        self._rid_aidx[rid] = self._aidx_stash.pop(slot, 0)
        self._slot_req[slot] = rid
        self._tokens[rid] = [int(first)]
        self._budget[rid] = cfg.max_new_tokens - 1
        self._cfg[rid] = cfg
        if bool(tok_done) or self._budget[rid] <= 0:
            self._retire(slot)
        if monitor.enabled():
            monitor.histogram(
                "paddle_tpu_kv_admission_seconds",
                "add_request latency: prefill + cache install + slot "
                "state update").observe(time.perf_counter() - t0)
            monitor.counter(
                "paddle_tpu_requests_total",
                "serving requests by lifecycle event",
                ("event",)).labels(event="admitted").inc()
            # the prompt's first generated token is sampled HERE, not in
            # a decode segment — count it so tokens_total means tokens
            monitor.counter(
                "paddle_tpu_generated_tokens_total",
                "tokens generated by the continuous-batching engines "
                "(admission first-token + decode segments)").inc()
        return rid

    # -- bounded-compile prefill helpers -------------------------------------
    def _prefill_width(self, plen: int) -> int:
        """Pad target for a plen-token prompt (plen itself when
        bucketing is disabled)."""
        if self.prefill_buckets is None:
            return plen
        return _bucket_for(self.prefill_buckets, plen)

    def _count_prefill(self, bucket) -> None:
        if monitor.enabled():
            monitor.counter(
                "paddle_tpu_prefill_requests_total",
                "admission prefills by engine and padded bucket width "
                "('chunked' = chunked admission)",
                ("engine", "bucket")).labels(
                engine=self._monitor_engine, bucket=str(bucket)).inc()

    def _run_prefill(self, ids, plen: int, mini, aidx: int = 0):
        """Pad the prompt to its bucket and run the one-shot prefill
        program (under the request's adapter, when any); returns
        (last-position logits [1, V], mini)."""
        width = self._prefill_width(plen)
        self._count_prefill(width if self.prefill_buckets is not None
                            else "exact")
        if trace.enabled():
            # the bucket CHOICE is the observable that explains a
            # prefill's latency class (compiled-program width)
            trace.event("engine.prefill", engine=self._monitor_engine,
                        plen=plen, bucket=width)
        return self._prefill(self.params, _pad_ids(ids, width), mini,
                             jnp.int32(plen - 1), self._bank(),
                             jnp.int32(aidx))

    def _admit_cache(self, slot: int, ids, plen: int, cfg):
        """Cache-layout hook: prefill the prompt and install its KV into
        slot's cache; returns the prompt's last-position logits. The
        dense base scatters a max_len mini cache; the paged subclass
        reserves pages and scatters a bucket-sized one."""
        mini = self._mini_cache(self.max_len)
        last_logits, mini = self._run_prefill(
            ids, plen, mini, aidx=self._aidx_stash.get(slot, 0))
        self._install_mini(slot, mini, plen)
        return last_logits

    def _reserve_admit(self, slot: int, plen: int, cfg) -> None:
        """Claim everything (beyond the slot) the admission will need UP
        FRONT — the paged override reserves the worst-case pages — so a
        chunked admission can never fail for capacity halfway through."""

    def _install_mini(self, slot: int, mini, plen: int) -> None:
        """Install a prefilled mini cache into ``slot``'s share of the
        pool (dense: scatter the max_len slab row)."""
        self.caches = self._admit(self.caches, mini, jnp.int32(slot))

    def _abort_admit(self, slot: int) -> None:
        """Undo a failed admission's capacity claim (slot back to the
        free list, adapter reference released; the paged override also
        releases pages)."""
        aidx = self._aidx_stash.pop(slot, 0)
        if aidx and self.adapters is not None:
            self.adapters.release(aidx)
        heapq.heappush(self._free, slot)

    def _retire(self, slot, event: str = "finished"):
        rid = self._slot_req.pop(slot)
        # lint: allow-host-sync(host-list copy: _tokens is python-side
        # bookkeeping, no device read happens here)
        self._finished[rid] = np.asarray(self._tokens.pop(rid), np.int32)
        del self._budget[rid]
        self._cfg.pop(rid, None)
        self._spec.pop(rid, None)
        aidx = self._rid_aidx.pop(rid, 0)
        if aidx and self.adapters is not None:
            # last live reference completes a deferred unload; the
            # device vector keeps the stale index for this dead slot —
            # harmless (dead rows are masked, and the index is only
            # rewritten when a future load recycles it)
            self.adapters.release(aidx)
        self.active_dev = self.active_dev.at[slot].set(False)
        # drop the slot's sampled flag so an all-greedy batch regains
        # the _sample_rows fast path once sampled requests retire
        self.samp["sample"] = self.samp["sample"].at[slot].set(False)
        # heap, not append+sort: retire/abort run in the latency-critical
        # inter-segment gap, and admission must stay deterministic
        # (lowest free slot first) without an O(n log n) sort per event
        heapq.heappush(self._free, slot)
        if monitor.enabled():
            monitor.counter(
                "paddle_tpu_requests_total",
                "serving requests by lifecycle event",
                ("event",)).labels(event=event).inc()

    def _evict_active(self, rid: int, event: str):
        """Shared reclaim for the early-removal paths (cancel, preempt):
        retire ``rid``'s slot — capacity back to the pool, request never
        in ``collect_finished()`` — and return its partial tokens
        (np.int32), or None when ``rid`` is not active."""
        slot = next((s for s, r in self._slot_req.items() if r == rid),
                    None)
        if slot is None:
            return None
        out = np.asarray(self._tokens[rid], np.int32)
        self._retire(slot, event=event)
        self._finished.pop(rid, None)
        return out

    def cancel_request(self, rid: int):
        """Cancel an ACTIVE request and reclaim its capacity: the slot
        (and, paged, its pages) returns to the pool immediately and the
        request never appears in ``collect_finished()``. Returns the
        partial tokens generated so far (np.int32), or None when ``rid``
        is not active (unknown, already finished, or already cancelled).

        Call only from the thread driving the engine, BETWEEN decode
        segments — the serving scheduler applies user ``cancel()`` flags
        at the next inter-segment gap, which is what keeps cancelled
        slots from leaking mid-segment."""
        return self._evict_active(rid, "cancelled")

    def partial_tokens(self, rid: int, start: int = 0):
        """Copy of the tokens generated so far for an ACTIVE request,
        from position ``start`` (the token-streaming hook: schedulers
        pass the count they already pushed so each inter-segment gap
        copies one segment's delta, not the whole growing history), or
        None when ``rid`` is not active."""
        toks = self._tokens.get(rid)
        return None if toks is None else list(toks[start:])

    # -- supervised recovery (host-driven, engine-owning thread only) --------
    def reset_state(self) -> None:
        """Drop EVERY request and rebuild the engine's device-side
        decode state from scratch: fresh caches, lengths, done/active
        flags, per-slot sampling vectors, and a full free-slot list
        (paged: the whole page pool). Compiled programs are KEPT — after
        an engine-scoped fault (:class:`EngineFault`, a device error mid
        ``decode_segment``) the device arrays are suspect but the jitted
        programs are not, so a supervised restart pays device re-init
        plus replay prefills, never a recompile.

        In-flight requests are forgotten, not finished: the caller (the
        serving scheduler's recovery path) owns replaying them from
        their stored prompt + tokens emitted so far. ``_next_req`` is
        NOT reset — request ids stay unique across restarts, so a stale
        pre-restart rid can never alias a replayed request."""
        # drop the old pool BEFORE the rebuild allocates the new one:
        # both alive at once would double peak KV HBM at the exact
        # moment (device-fault recovery, pool sized near capacity) a
        # second pool cannot fit
        self.caches = None
        self._init_decode_state()
        self._slot_req.clear()
        self._tokens.clear()
        self._budget.clear()
        self._cfg.clear()
        self._spec.clear()
        self._finished.clear()
        # every live adapter reference was just forgotten with its
        # slot; the bank and name map SURVIVE (adapters are weights —
        # a supervised restart must not lose them), deferred unloads
        # complete now that nothing references them
        self._aidx_stash.clear()
        self._rid_aidx.clear()
        if self.adapters is not None:
            self.adapters.release_all()
        if monitor.enabled():
            monitor.counter(
                "paddle_tpu_requests_total",
                "serving requests by lifecycle event",
                ("event",)).labels(event="engine_reset").inc()

    # -- multi-tenant LoRA (host-driven, between segments) -------------------
    def load_adapter(self, name: str, params: dict, alpha=None) -> int:
        """Hot-load one LoRA adapter into the device bank; returns its
        bank index. ``params`` maps target projection names to
        ``(A, B)`` factor pairs (see
        :meth:`~paddle_tpu.serving.adapters.AdapterRegistry.load`).
        Only rewrites bank ROWS — the compiled serving programs are
        untouched, so a load costs zero recompiles (post-``warmup``,
        zero compiles at all).

        Like ``cancel_request``: call only from the thread driving the
        engine, BETWEEN decode segments — the serving scheduler's
        ``Server.load_adapter`` marshals into the inter-segment gap."""
        if self.adapters is None:
            raise RuntimeError(
                "engine built without lora_capacity; pass "
                "lora_capacity=K at construction")
        return self.adapters.load(name, params, alpha=alpha)

    def unload_adapter(self, name: str) -> bool:
        """Hot-unload an adapter. Returns True when its bank index
        freed immediately; False when live requests still decode under
        it — the unload DEFERS (new requests naming it are rejected at
        admission; the index frees, and becomes recyclable, when the
        last live slot retires). Same thread contract as
        :meth:`load_adapter`."""
        if self.adapters is None:
            raise RuntimeError(
                "engine built without lora_capacity; pass "
                "lora_capacity=K at construction")
        return self.adapters.unload(name)

    # -- chunked admission (host-driven, one chunk per inter-segment gap) ----
    def begin_admit(self, prompt_ids, cfg: GenerationConfig):
        """Start a CHUNKED admission: claim the slot AND (paged) the
        request's worst-case pages up front — the existing
        ``_can_admit``/``_abort_admit`` contract, so a partial admission
        can never leak capacity or fail for capacity halfway through —
        then return the admission object. The caller (the serving
        scheduler's gap) drives ONE fixed-shape prefill chunk per
        :meth:`admit_chunk` call, interleaving decode segments between
        chunks so a long prompt never monopolizes the gap.

        Raises like ``add_request`` when the request cannot be admitted
        RIGHT NOW (probe :meth:`can_admit` first) and RuntimeError when
        the engine was built without ``prefill_chunk``."""
        if self.prefill_chunk is None:
            raise RuntimeError(
                "chunked admission needs an engine built with "
                "prefill_chunk=<tokens>")
        if not self._free:
            raise RuntimeError("no free slot; drain with decode_segment()")
        ids = _prompt_ids(prompt_ids)
        plen = ids.shape[1]
        if plen + cfg.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt({plen}) + max_new_tokens({cfg.max_new_tokens}) "
                f"exceeds engine max_len({self.max_len})")
        if not self._can_admit(plen, cfg):
            raise RuntimeError(
                "page pool exhausted; drain with decode_segment()")
        aidx = self._acquire_adapter(cfg)
        slot = heapq.heappop(self._free)
        # the adapter reference is claimed for the WHOLE chunked
        # admission (an unload defers while chunks are still running);
        # _register transfers it to the rid, _abort_admit releases it
        self._aidx_stash[slot] = aidx
        try:
            mini, start = self._begin_admit_cache(slot, ids, plen, cfg)
        except BaseException:
            self._abort_admit(slot)
            raise
        rid = self._next_req
        self._next_req += 1
        self._count_prefill("chunked")
        return _ChunkedAdmission(rid, slot, ids, plen, cfg, mini,
                                 off=start)

    def _begin_admit_cache(self, slot: int, ids, plen: int, cfg):
        """Claim a chunked admission's capacity and build its mini
        cache; returns ``(mini, chunk_start)``. Base: reserve via
        ``_reserve_admit`` and start chunking at 0 — chunk programs are
        keyed on the FIXED (chunk, max_len) shapes, so every chunked
        admission shares one compiled program (the paged engine pays a
        transient dense mini slab for the admission's lifetime — same
        slab the dense engine always uses). The paged prefix-cache
        override maps cached prefix pages first and starts chunking
        past them."""
        self._reserve_admit(slot, plen, cfg)
        return self._mini_cache(self.max_len), 0

    def admit_chunk(self, adm: _ChunkedAdmission) -> bool:
        """Run ONE fixed-shape prefill chunk of an admission started
        with :meth:`begin_admit`. Returns True when the admission
        completed — the request is live in its slot under ``adm.rid``
        (its first token is in ``partial_tokens``). On ANY failure the
        claimed capacity is reclaimed and the admission is closed."""
        if adm.closed:
            raise RuntimeError("admission already completed or aborted")
        C = self.prefill_chunk
        try:
            aidx = self._aidx_stash.get(adm.slot, 0)
            chunk = adm.ids[:, adm.off:adm.off + C]
            r = chunk.shape[1]
            last = adm.off + r >= adm.plen
            if r < C:       # only the FINAL chunk may be partial
                chunk = _pad_ids(chunk, C)
            adm.last_logits, adm.mini = self._prefill_chunk(
                self.params, chunk, adm.mini, jnp.int32(adm.off),
                jnp.int32(r - 1), self._bank(), jnp.int32(aidx))
            adm.off += C
            adm.chunks_done += 1
            if monitor.enabled():
                monitor.counter(
                    "paddle_tpu_prefill_chunks_total",
                    "fixed-shape prefill chunks run by chunked "
                    "admissions", ("engine",)).labels(
                    engine=self._monitor_engine).inc()
            if not last:
                return False
            self._install_mini(adm.slot, adm.mini, adm.plen)
            first, tok_done = self._sample_first(adm.rid,
                                                 adm.last_logits,
                                                 adm.cfg)
            self._install_state(adm.slot, adm.plen, first, tok_done,
                                adm.cfg, aidx=aidx, ids=adm.ids)
        except BaseException:
            adm.closed = True
            self._abort_admit(adm.slot)
            raise
        adm.closed = True
        self._init_spec(adm.rid, adm.ids, first, adm.cfg)
        self._register(adm.slot, adm.rid, first, tok_done, adm.cfg,
                       adm.t0)
        return True

    def abort_admit(self, adm: _ChunkedAdmission) -> None:
        """Abandon an in-flight chunked admission (client cancelled mid
        prefill): the slot and any page reservation return to the pool.
        Idempotent; the admission is closed either way."""
        if adm.closed:
            return
        adm.closed = True
        self._abort_admit(adm.slot)

    # -- warmup (off the request path) ---------------------------------------
    def warmup(self, segment_steps: Optional[int] = None):
        """Pre-compile every program a request can hit on the serving
        path — one prefill per bucket, the chunked-prefill program, the
        cache-install and slot-state programs, and (when
        ``segment_steps`` is given) the decode segment — so no user
        request ever pays an XLA compile inside the latency-critical
        gap. Compile time lands on the existing ``monitored_jit``
        counters (``paddle_tpu_jit_cache_miss_total`` /
        ``jit_compile_seconds_total``). Only valid on an IDLE engine;
        returns {program_name: seconds}.
        """
        if self._slot_req:
            raise RuntimeError("warmup() needs an idle engine")
        t_all = time.perf_counter()
        out = {}
        # with bucketing DISABLED prompt lengths (and so prefill
        # programs) are unbounded — warmup cannot cover them, so it
        # warms only the length-independent programs
        widths = self.prefill_buckets or ()
        for w in widths:
            t0 = time.perf_counter()
            ids = np.zeros((1, w), np.int32)
            mini = self._warmup_mini(w)
            _, mini = self._prefill(self.params, ids, mini,
                                    jnp.int32(w - 1), self._bank(),
                                    jnp.int32(0))
            # also warms the per-bucket cache-install program; slot 0 is
            # free, so the zero-prompt KV it scatters is dead weight the
            # next admission overwrites (paged: dropped — no pages
            # mapped)
            self._install_mini(0, mini, w)
            out[f"prefill_{w}"] = time.perf_counter() - t0
        if self.prefill_chunk is not None:
            t0 = time.perf_counter()
            mini = self._mini_cache(self.max_len)
            self._prefill_chunk(self.params,
                                np.zeros((1, self.prefill_chunk),
                                         np.int32),
                                mini, jnp.int32(0), jnp.int32(0),
                                self._bank(), jnp.int32(0))
            out["prefill_chunk"] = time.perf_counter() - t0
        # slot-state install program (values match the initial state,
        # except the active flag — reset below)
        t0 = time.perf_counter()
        self._install_state(0, 0, jnp.int32(0), jnp.asarray(False),
                            GenerationConfig(max_new_tokens=1))
        self.active_dev = self.active_dev.at[0].set(False)
        out["admit_state"] = time.perf_counter() - t0
        if segment_steps is not None:
            # with every slot inactive the segment is a semantic no-op
            # (live rows mask to nothing), so running it only compiles
            t0 = time.perf_counter()
            key = jax.random.PRNGKey(0)
            (_, self.last, self.lens, self.done_dev, self.caches) = \
                self._segment_fn(segment_steps)(
                    self.params, self.last, self.lens, self.done_dev,
                    self.active_dev, self.samp, self._bank(),
                    self.caches, key)
            out[f"segment_{segment_steps}"] = time.perf_counter() - t0
        if self.draft_k and self.spec_mode == "host":
            # the widened speculative verify step: with every slot
            # inactive (live mask all-False) acceptance is 0 and every
            # KV write drops, so running it only compiles
            t0 = time.perf_counter()
            mb = self.max_batch
            (_, _, self.last, self.lens, self.caches) = \
                self._spec_step_fn()(
                    self.params, self.last, self.lens, self.active_dev,
                    self.samp, self._bank(), self.caches,
                    jax.random.PRNGKey(0),
                    jnp.zeros((mb, self.draft_k), jnp.int32),
                    jnp.zeros((mb,), bool), jnp.zeros((mb,), jnp.int32))
            out[f"spec_step_{self.draft_k}"] = time.perf_counter() - t0
        if (self.draft_k and self.spec_mode == "device"
                and segment_steps is not None):
            # the fused device-resident speculative segment: like the
            # plain segment warm, all-inactive rows make every step a
            # masked no-op, so running it only compiles — the program
            # a speculating request hits is hot before the first
            # admission
            t0 = time.perf_counter()
            mb = self.max_batch
            (_, self.last, self.lens, self.done_dev, self.hist,
             self.hist_len, self.caches) = \
                self._spec_segment_device_fn(segment_steps)(
                    self.params, self.last, self.lens, self.done_dev,
                    self.active_dev, self.samp, self._bank(),
                    self.caches, self.hist, self.hist_len,
                    jnp.zeros((mb,), jnp.int32),
                    jnp.zeros((mb,), jnp.int32), jax.random.PRNGKey(0))
            out[f"spec_segment_{segment_steps}"] = \
                time.perf_counter() - t0
        if self.adapters is not None:
            # per-target bank-row install programs: the first hot
            # load() in a serving gap must not pay an XLA compile
            t0 = time.perf_counter()
            self.adapters.warmup()
            out["lora_install"] = time.perf_counter() - t0
        out.update(self._warmup_prefix())
        out["total"] = time.perf_counter() - t_all
        if monitor.enabled():
            monitor.gauge(
                "paddle_tpu_prefill_warmup_seconds",
                "wall seconds engine.warmup() spent pre-compiling the "
                "serving-path programs", ("engine",)).labels(
                engine=self._monitor_engine).set(out["total"])
        return out

    def _warmup_mini(self, width: int):
        """Mini cache matching what an admission of a width-token prompt
        allocates (dense: the max_len slab; paged: bucket-sized)."""
        return self._mini_cache(self.max_len)

    def _warmup_prefix(self) -> dict:
        """Pre-compile the prefix-cache warm-admission programs (paged
        engine with ``prefix_cache=True``; no-op otherwise)."""
        return {}

    def _segment_fn(self, n_steps: int):
        # keyed on n_steps ALONE: sampling parameters AND the LoRA
        # adapter index ride as per-slot device vectors (_sample_rows /
        # the bank gather), so a server facing arbitrary per-request
        # GenerationConfigs — any adapter mix included — never
        # recompiles the segment
        if n_steps not in self._segment_cache:
            max_len = self.max_len

            def segment(params, last, lens, done, active, samp, bank,
                        caches, key):
                lora = (bank, samp["adapter"]) if bank else None

                def step(carry, _):
                    last, lens, done, caches, key = carry
                    live = active & ~done & (lens < max_len)
                    logits, caches = self._fwd_ragged(
                        params, last[:, None], caches, lens, live,
                        lora)
                    key, sub = jax.random.split(key)
                    nxt = _sample_rows(logits[:, 0], sub, samp)
                    nxt = jnp.where(live, nxt, last)
                    lens = lens + live.astype(jnp.int32)
                    done = done | (live & (samp["eos"] >= 0)
                                   & (nxt == samp["eos"]))
                    done = done | (lens >= max_len)
                    return (nxt, lens, done, caches, key), nxt

                (last, lens, done, caches, _), toks = jax.lax.scan(
                    step, (last, lens, done, caches, key), None,
                    length=n_steps)
                return (jnp.swapaxes(toks, 0, 1), last, lens, done,
                        caches)

            self._segment_cache[n_steps] = monitor.monitored_jit(
                segment, name="cb_segment",
                owner=self._monitor_engine, donate_argnums=(7,))
        return self._segment_cache[n_steps]

    # -- batched speculative decoding (per-slot capability) ------------------
    def _fwd_spec(self, params, inp, caches, lens, live, lora=None):
        """W-token verify forward at per-row offsets (cache-layout
        hook; the paged subclass routes through the page pool).
        Returns ``(logits, caches, aux)`` — ``aux`` is the window-write
        rows the int8 paged path hands back for the post-acceptance
        commit (:meth:`_commit_spec_rows`); ``None`` here (dense
        caches write exact floats, rejected rows are plain overwritten
        garbage)."""
        from ..core.autograd import no_grad

        with substituted_state(self.model, params), no_grad():
            logits, caches = self.model.forward_decode_spec(
                Tensor(inp), caches, lens, live,
                **self._fwd_kwargs(lora))
        return (logits.value if isinstance(logits, Tensor) else logits,
                caches, None)

    def _commit_spec_rows(self, caches, aux, n_acc):
        """Post-acceptance KV commit for the verify window: restore
        each layer's pre-window snapshot (touched pages + scale
        tables), then REPLAY only the accepted rows (``i < n_acc[b]``)
        sequentially through the running-absmax int8 primitive.

        The verify forward stored the whole W-window with running
        scales so in-window reads match sequential plain decode
        bitwise on acceptance-matched positions — but a rejected
        draft's absmax must never persist in a page's MONOTONIC
        running scale (the plain path never writes those rows).
        Restore-then-replay makes the persistent pool/scale state
        byte-for-byte what W single-token decode stores of the
        accepted tokens would have produced: same scale-growth events,
        same requant cascades, same rounding order — so spec-vs-plain
        token parity survives quantization. No-op on dense/bf16 caches
        (``aux`` is None — their rejected rows are exact-overwritten
        garbage, nothing persists)."""
        if aux is None or not any(a is not None for a in aux):
            return caches
        from ..quantization.kv import quant_store_rows

        pools, pt = caches
        new_pools = []
        for (kp, vp, ks, vs), \
                (snap_k, snap_v, snap_ks, snap_vs,
                 kh, vh, page, offs) in zip(pools, aux):
            w = page.shape[1]
            pf = page.reshape(-1)
            # un-write the window: duplicate pages in the snapshot
            # gathered identical pre-store bytes, so duplicate
            # scatter-backs are deterministic
            kp = kp.at[pf].set(snap_k, mode="drop")
            vp = vp.at[pf].set(snap_v, mode="drop")
            ks, vs = snap_ks, snap_vs
            for i in range(w):
                pg = jnp.where(jnp.asarray(i, jnp.int32) < n_acc,
                               page[:, i], kp.shape[0])
                kp, ks = quant_store_rows(kp, ks, pg, offs[:, i],
                                          kh[:, i])
                vp, vs = quant_store_rows(vp, vs, pg, offs[:, i],
                                          vh[:, i])
            new_pools.append((kp, vp, ks, vs))
        return new_pools, pt

    def _spec_step_fn(self):
        """ONE compiled speculative verify step, keyed on the engine's
        ``draft_k`` alone: every slot — speculating, plain greedy, or
        sampled — rides the same program.

        Each row's input window is ``[last, d_0..d_{k-1}]`` (W = k+1
        positions at its own offset). The forward writes all W K/V
        rows and returns logits per position; position i's greedy
        token g_i was computed from the true prefix whenever the
        drafts matched up to i, so the emitted tokens are ALWAYS
        ``g_0..g_{n_acc-1}`` — the model's own greedy continuation —
        and acceptance only decides HOW MANY are sound:

        - ``m`` = leading draft/greedy matches, capped per row at its
          ``spec_k`` (0 for plain rows → exactly one token per step);
        - ``n_acc = min(m + 1, lim - lens)`` — ``lim`` is the host's
          per-row absolute cap (budget + page coverage + max_len), so
          accepted tokens always have VALID cache writes behind them
          (writes past coverage/max_len are dropped; the positions
          whose logits they'd poison are exactly the capped-away
          ones);
        - sampled rows take ``_sample_rows`` on position 0 and force
          ``n_acc = 1`` (their spec_k is 0).

        Rejected-draft K/V past ``lens + n_acc`` is stale by the same
        convention the offline path documents: every read is
        length-masked and later writes overwrite it."""
        key_ = ("spec_step", self.draft_k)
        if key_ not in self._segment_cache:
            k = self.draft_k

            def spec_step(params, last, lens, active, samp, bank,
                          caches, key, drafts, live_in, lim):
                b = last.shape[0]
                lora = (bank, samp["adapter"]) if bank else None
                live = live_in & active & (lens < self.max_len)
                inp = jnp.concatenate([last[:, None], drafts], axis=1)
                logits, caches, aux = self._fwd_spec(
                    params, inp, caches, lens, live, lora)
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                key, sub = jax.random.split(key)
                g0 = jnp.where(samp["sample"],
                               _sample_rows(logits[:, 0], sub, samp),
                               greedy[:, 0])
                toks = jnp.concatenate([g0[:, None], greedy[:, 1:]],
                                       axis=1)            # [B, W]
                iw = jnp.arange(k, dtype=jnp.int32)[None]
                match = ((drafts == greedy[:, :k])
                         & (iw < samp["spec_k"][:, None]))
                m = jnp.sum(jnp.cumprod(match.astype(jnp.int32),
                                        axis=1), axis=1)
                n_acc = jnp.minimum(m + 1,
                                    jnp.maximum(lim - lens, 0))
                n_acc = jnp.where(live, n_acc, 0)
                caches = self._commit_spec_rows(caches, aux, n_acc)
                new_last = jnp.where(
                    n_acc > 0,
                    toks[jnp.arange(b), jnp.maximum(n_acc - 1, 0)],
                    last)
                return toks, n_acc, new_last, lens + n_acc, caches

            self._segment_cache[key_] = monitor.monitored_jit(
                spec_step, name="cb_spec_step",
                owner=self._monitor_engine, donate_argnums=(6,))
        return self._segment_cache[key_]

    def _coverage_limit(self, slot: int) -> int:
        """Absolute position this slot's cache writes are valid up to
        (dense slabs: the whole cache; the paged engine reports the
        slot's mapped pages) — the spec step's per-row acceptance cap,
        so a window reaching past grown coverage degrades to fewer
        accepted tokens, never to reads of dropped writes."""
        return self.max_len

    def _spec_segment_device_fn(self, n_steps: int):
        """ONE fused compiled speculative segment
        (``spec_mode="device"``): propose → W-position verify → accept
        → KV-write for ``n_steps`` steps inside a single ``lax.scan``,
        keyed on ``(n_steps, draft_k)`` alone (plus the engine-level
        ``spec_draft`` source, an idle-only knob). The draft source is
        the per-slot history ring — ``ngram.propose_device``, the host
        proposer's windowed twin — or, under ``spec_draft="self"``,
        the previous verify's trailing greedy tokens (EAGLE-lite; the
        ring still bootstraps each segment's first step). Budget, eos
        and page-coverage caps are device masks per step (``bud`` /
        ``cov`` are per-row vectors from pure host bookkeeping —
        coverage is FIXED across a segment because page growth only
        happens in the inter-segment gap), so the host reads back once
        per SEGMENT instead of once per verify step.

        Acceptance is byte-for-byte the host path's
        (:meth:`_spec_step_fn`): emitted tokens are always the model's
        own greedy picks ``g_0..g_{n_acc-1}``, drafts only decide HOW
        MANY — which is why device/host/plain greedy parity is
        structural, even for a context that outgrew the ring. Per-step
        tokens, acceptance counts, liveness AND the final done flags
        all ride one packed int32 output tensor, so collection is
        literally one readback."""
        key_ = ("spec_device", n_steps, self.draft_k, self.spec_draft)
        if key_ not in self._segment_cache:
            k = self.draft_k
            W = k + 1
            max_len = self.max_len
            n_max = self.ngram_max
            H = self.spec_history
            self_draft = self.spec_draft == "self"

            def spec_segment(params, last, lens, done, active, samp,
                             bank, caches, hist, hl, bud, cov, key):
                b = last.shape[0]
                lora = (bank, samp["adapter"]) if bank else None
                rows = jnp.arange(b)
                iw = jnp.arange(k, dtype=jnp.int32)[None]

                def step(carry, _):
                    (last, lens, done, caches, hist, hl, drafts,
                     emitted, key) = carry
                    live = (active & ~done & (lens < max_len)
                            & (emitted < bud))
                    if not self_draft:
                        drafts = propose_device(hist, hl, k, n_max)
                    inp = jnp.concatenate([last[:, None], drafts],
                                          axis=1)
                    logits, caches, aux = self._fwd_spec(
                        params, inp, caches, lens, live, lora)
                    greedy = jnp.argmax(logits, axis=-1).astype(
                        jnp.int32)
                    key, sub = jax.random.split(key)
                    g0 = jnp.where(
                        samp["sample"],
                        _sample_rows(logits[:, 0], sub, samp),
                        greedy[:, 0])
                    toks = jnp.concatenate([g0[:, None], greedy[:, 1:]],
                                           axis=1)          # [B, W]
                    match = ((drafts == greedy[:, :k])
                             & (iw < samp["spec_k"][:, None]))
                    m = jnp.sum(jnp.cumprod(match.astype(jnp.int32),
                                            axis=1), axis=1)
                    # per-row absolute cap, fused: remaining budget
                    # (bud - emitted) + page coverage; cov is already
                    # min(coverage, max_len) host-side
                    lim = jnp.minimum(
                        lens + jnp.maximum(bud - emitted, 0), cov)
                    n_acc = jnp.minimum(m + 1,
                                        jnp.maximum(lim - lens, 0))
                    n_acc = jnp.where(live, n_acc, 0)
                    # eos mid-accepted-draft: truncate at the FIRST
                    # accepted eos and freeze the row — the host
                    # loop's cut, as a device mask
                    hit = ((samp["eos"][:, None] >= 0)
                           & (toks == samp["eos"][:, None])
                           & (jnp.arange(W)[None] < n_acc[:, None]))
                    any_hit = hit.any(axis=1)
                    n_acc = jnp.where(
                        any_hit,
                        jnp.argmax(hit, axis=1).astype(jnp.int32) + 1,
                        n_acc)
                    done = done | any_hit
                    # int8 paged pools: running-absmax commit of the
                    # FINAL accepted prefix only (post-eos-truncation)
                    # — rejected rows stay scale-frozen
                    caches = self._commit_spec_rows(caches, aux, n_acc)
                    new_last = jnp.where(
                        n_acc > 0,
                        toks[rows, jnp.maximum(n_acc - 1, 0)], last)
                    lens = lens + n_acc
                    done = done | (lens >= max_len)
                    emitted = emitted + n_acc
                    # history-ring append of the VARIABLE per-row
                    # accepted count: masked scatter into an H+W
                    # extension (out-of-range columns drop), then a
                    # per-row gather shift keeps the last H tokens
                    ext = jnp.concatenate(
                        [hist, jnp.zeros((b, W), jnp.int32)], axis=1)
                    cols = hl[:, None] + jnp.arange(W)[None]
                    cols = jnp.where(
                        jnp.arange(W)[None] < n_acc[:, None], cols,
                        H + W)
                    ext = ext.at[rows[:, None], cols].set(toks,
                                                          mode="drop")
                    shift = jnp.maximum(hl + n_acc - H, 0)
                    hist = jnp.take_along_axis(
                        ext, jnp.arange(H)[None] + shift[:, None],
                        axis=1)
                    hl = jnp.minimum(hl + n_acc, H)
                    if self_draft:
                        # next drafts = this verify's trailing greedy
                        # tokens past the accepted prefix (clamped to
                        # the window) — position lens+n_acc's
                        # continuation guess came from THIS forward
                        nxt = jnp.take_along_axis(
                            toks, jnp.clip(n_acc[:, None] + iw, 0, k),
                            axis=1)
                        drafts = jnp.where(live[:, None], nxt, drafts)
                    ys = jnp.concatenate(
                        [toks, n_acc[:, None],
                         live.astype(jnp.int32)[:, None]], axis=1)
                    return ((new_last, lens, done, caches, hist, hl,
                             drafts, emitted, key), ys)

                drafts0 = (propose_device(hist, hl, k, n_max)
                           if self_draft
                           else jnp.zeros((b, k), jnp.int32))
                carry = (last, lens, done, caches, hist, hl, drafts0,
                         jnp.zeros((b,), jnp.int32), key)
                (last, lens, done, caches, hist, hl, _, _, _), seg = \
                    jax.lax.scan(step, carry, None, length=n_steps)
                # final done flags ride the SAME packed tensor as the
                # per-step tokens: collection is one readback
                tail = jnp.zeros((1, b, W + 2),
                                 jnp.int32).at[0, :, 0].set(
                    done.astype(jnp.int32))
                return (jnp.concatenate([seg, tail], axis=0), last,
                        lens, done, hist, hl, caches)

            self._segment_cache[key_] = monitor.monitored_jit(
                spec_segment, name="cb_spec_device_segment",
                owner=self._monitor_engine, donate_argnums=(7,))
        return self._segment_cache[key_]

    # lint: hot-path
    def _decode_segment_spec_device(self, n_steps: int,
                                    cfg=None):
        """Device-resident speculative decode segment: ONE dispatch of
        the fused :meth:`_spec_segment_device_fn` program, then ONE
        readback for collection — no per-verify-step host round-trip
        (``spec_stats()["host_syncs"]`` stays 0 in this mode; that
        round-trip is exactly what ``spec_mode="host"`` pays).

        The per-row budget/coverage caps ship as fixed-shape device
        vectors built from pure host bookkeeping — never a device
        pull, never a recompile — and the segment's speculative
        accounting (proposed/accepted/slot_steps) is derived ONCE from
        the packed per-step tallies the program returns, preserving
        the ``emitted == slot_steps + accepted`` identity across both
        modes."""
        t0 = time.perf_counter()
        mb = self.max_batch
        k = self.draft_k
        W = k + 1
        bud = np.zeros((mb,), np.int32)
        cov = np.zeros((mb,), np.int32)
        for slot, rid in self._slot_req.items():
            bud[slot] = max(self._budget[rid], 0)
            cov[slot] = min(self._coverage_limit(slot), self.max_len)
        # fresh noise per segment, like the plain scan (the program
        # splits per step; sampled rows fold their own seed in)
        self._segments_run += 1
        key = jax.random.fold_in(
            jax.random.PRNGKey(cfg.seed if cfg is not None else 0),
            self._segments_run)
        (seg, self.last, self.lens, self.done_dev, self.hist,
         self.hist_len, self.caches) = self._spec_segment_device_fn(
            n_steps)(
            self.params, self.last, self.lens, self.done_dev,
            self.active_dev, self.samp, self._bank(), self.caches,
            self.hist, self.hist_len, jnp.asarray(bud),
            jnp.asarray(cov), key)
        # lint: allow-host-sync(collection itself: ONE readback per
        # FUSED segment — n_steps x (tokens, acceptance, liveness)
        # plus the final done flags ride one packed tensor; this is
        # the plain path's once-per-segment collect pull, not the
        # host-mode per-verify-step sync)
        seg = np.asarray(seg)
        done_h = seg[-1, :, 0].astype(bool)
        total = proposed = accepted = slot_steps = 0
        steps_live = np.zeros((n_steps,), bool)
        for slot, rid in list(self._slot_req.items()):
            live_s = seg[:n_steps, slot, W + 1].astype(bool)
            acc_s = seg[:n_steps, slot, W]
            sk = self._spec_k_of(rid)
            seq = []
            for s in range(n_steps):
                if not live_s[s]:
                    continue
                steps_live[s] = True
                slot_steps += 1
                proposed += sk
                na = int(acc_s[s])
                seq.extend(int(t) for t in seg[s, slot, :na])
                accepted += max(na - 1, 0)
            self._tokens[rid].extend(seq)
            self._budget[rid] -= len(seq)
            total += len(seq)
            if self._budget[rid] <= 0 or bool(done_h[slot]):
                self._retire(slot)
        # forwards counts verify steps that served at least one live
        # row — the host loop's early-exit semantics; the fused
        # program's trailing all-dead steps are masked no-ops
        forwards = int(steps_live.sum())
        self._spec_totals["proposed"] += proposed
        self._spec_totals["accepted"] += accepted
        self._spec_totals["forwards"] += forwards
        self._spec_totals["slot_steps"] += slot_steps
        self._spec_totals["emitted"] += total
        if monitor.enabled():
            dt = time.perf_counter() - t0
            monitor.counter(
                "paddle_tpu_generated_tokens_total",
                "tokens generated by the continuous-batching engines "
                "(admission first-token + decode segments)").inc(total)
            self._tokens_per_sec_gauge().labels(
                engine=self._monitor_engine).set(
                total / dt if dt > 0 else 0.0)
            if proposed:
                c = self._spec_tokens_counter()
                c.labels(engine=self._monitor_engine,
                         outcome="proposed").inc(proposed)
                c.labels(engine=self._monitor_engine,
                         outcome="accepted").inc(accepted)
        if trace.enabled():
            trace.record(
                "engine.spec_segment",
                dur_ns=int((time.perf_counter() - t0) * 1e9),
                engine=self._monitor_engine, mode="device",
                steps=n_steps, forwards=forwards, proposed=proposed,
                accepted=accepted, emitted=total, host_syncs=0)
        return len(self._slot_req)

    @staticmethod
    def _spec_tokens_counter():
        return monitor.counter(
            "paddle_tpu_spec_draft_tokens_total",
            "speculative-decode draft tokens by engine and outcome "
            "(proposed = host n-gram drafts sent to verification; "
            "accepted = drafts the model's own greedy continuation "
            "confirmed — acceptance rate is accepted/proposed)",
            ("engine", "outcome"))

    def spec_stats(self) -> dict:
        """Engine-lifetime speculative-decoding accounting, host-side
        and monitor-independent: proposed/accepted draft tokens,
        verify forwards, slot participations, tokens emitted (spec
        segments only — plain segments keep their 1/step cadence).

        ``tokens_per_forward`` is PER-SLOT — ``emitted / slot_steps``,
        one slot's tokens per verify forward it rode (1.0 = plain
        cadence; the batch-level tokens/forward would conflate batch
        size with speculation). At B=1 it reduces to the offline
        path's ``tokens/forwards`` metric.

        ``host_syncs`` counts blocking per-verify-step device→host
        readbacks (``spec_mode="host"``'s documented price — one per
        verify forward); ``host_syncs_per_token`` normalizes by
        emitted tokens and is structurally 0.0 under
        ``spec_mode="device"``, where the fused segment reads back
        once per segment like the plain path."""
        t = dict(self._spec_totals)
        t["acceptance_rate"] = (t["accepted"] / t["proposed"]
                                if t["proposed"] else 0.0)
        t["tokens_per_forward"] = (t["emitted"] / t["slot_steps"]
                                   if t["slot_steps"] else 0.0)
        t["host_syncs_per_token"] = (t["host_syncs"] / t["emitted"]
                                     if t["emitted"] else 0.0)
        return t

    # lint: hot-path
    def _decode_segment_spec(self, n_steps: int,
                             cfg: Optional[GenerationConfig] = None):
        """Speculative decode segment: ``n_steps`` verify steps of the
        ONE compiled ``_spec_step_fn`` program, with the host loop in
        between — propose fresh drafts from each slot's proposer,
        read back acceptance, stream/cut per slot (budget, eos)
        exactly like the plain path's collection does.

        The host round-trip per verify step is the price of host-side
        proposers (``spec_mode="host"``; ``"device"`` fuses the whole
        segment and pays NO per-step sync — see
        :meth:`_decode_segment_spec_device`); each forward yields up
        to ``spec_k + 1`` tokens for accepting rows, which is the
        trade this path exists to make (decode is HBM-bound on TPU, so
        accepted tokens/forward ≈ wall speedup there). Plain and
        sampled slots ride along at one token per step — a mixed batch
        never splits programs."""
        t0 = time.perf_counter()
        k = self.draft_k
        mb = self.max_batch
        fn = self._spec_step_fn()
        # lint: allow-host-sync(spec_mode="host" only — one lens/done
        # pull per SEGMENT: the host proposers need real lengths to
        # place drafts; tracked incrementally below, not re-pulled per
        # step. Device mode ships no per-row pulls at all.)
        lens_h = np.asarray(self.lens).copy()
        # lint: allow-host-sync(same once-per-segment spec_mode="host"
        # pull as lens_h)
        done_h = np.asarray(self.done_dev)
        emitted = {rid: [] for rid in self._slot_req.values()}
        finished = set()
        base = jax.random.PRNGKey(cfg.seed if cfg is not None else 0)
        forwards = 0
        proposed = accepted = slot_steps = 0
        for _ in range(n_steps):
            drafts = np.zeros((mb, k), np.int32)
            live = np.zeros((mb,), bool)
            lim = np.zeros((mb,), np.int32)
            for slot, rid in self._slot_req.items():
                if rid in finished or bool(done_h[slot]):
                    continue
                rem = self._budget[rid] - len(emitted[rid])
                if rem <= 0 or int(lens_h[slot]) >= self.max_len:
                    continue
                live[slot] = True
                lim[slot] = min(int(lens_h[slot]) + rem,
                                self._coverage_limit(slot),
                                self.max_len)
                prop = self._spec.get(rid)
                if prop is not None:
                    d = prop.propose()
                    drafts[slot, :len(d)] = d
                    proposed += prop.k
            if not live.any():
                break
            slot_steps += int(live.sum())
            # fresh noise per verify step, like the plain scan's
            # per-step key split (sampled rows fold their own seed in)
            self._segments_run += 1
            key = jax.random.fold_in(base, self._segments_run)
            toks, n_acc, self.last, self.lens, self.caches = fn(
                self.params, self.last, self.lens, self.active_dev,
                self.samp, self._bank(), self.caches, key,
                jnp.asarray(drafts), jnp.asarray(live),
                jnp.asarray(lim))
            forwards += 1
            # lint: allow-host-sync(the spec_mode="host" branch's
            # per-verify-step readback — host n-gram proposers must
            # see acceptance before drafting again. This is exactly
            # the sync spec_mode="device" eliminates; spec_stats'
            # host_syncs counts it, and it reads 0 in device mode.)
            toks_h = np.asarray(toks)
            # lint: allow-host-sync(same spec_mode="host"
            # per-verify-step readback)
            acc_h = np.asarray(n_acc)
            for slot, rid in self._slot_req.items():
                if not live[slot]:
                    continue
                na = int(acc_h[slot])
                lens_h[slot] += na
                seq = toks_h[slot, :na].tolist()
                rcfg = self._cfg[rid]
                if (rcfg.eos_token_id is not None
                        and rcfg.eos_token_id in seq):
                    # eos mid-accepted-draft: truncate host-side and
                    # finish the request — the stale device tail past
                    # eos dies with the slot's retirement
                    seq = seq[:seq.index(rcfg.eos_token_id) + 1]
                    finished.add(rid)
                emitted[rid].extend(int(t) for t in seq)
                prop = self._spec.get(rid)
                if prop is not None:
                    prop.extend(seq)
                    acc = max(len(seq) - 1, 0)
                    prop.accepted += acc
                    accepted += acc
        # collection: mirror the plain path's budget/eos retirement
        total = 0
        for slot, rid in list(self._slot_req.items()):
            seq = emitted.get(rid, [])
            self._tokens[rid].extend(seq)
            self._budget[rid] -= len(seq)
            total += len(seq)
            if (self._budget[rid] <= 0 or rid in finished
                    or bool(done_h[slot])):
                self._retire(slot)
        self._spec_totals["proposed"] += proposed
        self._spec_totals["accepted"] += accepted
        self._spec_totals["forwards"] += forwards
        self._spec_totals["slot_steps"] += slot_steps
        self._spec_totals["emitted"] += total
        # one blocking device→host readback per verify forward — the
        # host-mode price serve_bench's host-syncs-per-token record
        # surfaces (structurally 0 on the device-mode path)
        self._spec_totals["host_syncs"] += forwards
        if monitor.enabled():
            dt = time.perf_counter() - t0
            monitor.counter(
                "paddle_tpu_generated_tokens_total",
                "tokens generated by the continuous-batching engines "
                "(admission first-token + decode segments)").inc(total)
            self._tokens_per_sec_gauge().labels(
                engine=self._monitor_engine).set(
                total / dt if dt > 0 else 0.0)
            if proposed:
                c = self._spec_tokens_counter()
                c.labels(engine=self._monitor_engine,
                         outcome="proposed").inc(proposed)
                # inc(0) still creates the series: the acceptance rate
                # stays derivable (accepted/proposed) even at 0
                c.labels(engine=self._monitor_engine,
                         outcome="accepted").inc(accepted)
        if trace.enabled():
            # per-segment speculative accounting: acceptance explains
            # why a segment's emitted count beat (or matched) its
            # verify-forward count
            trace.record(
                "engine.spec_segment",
                dur_ns=int((time.perf_counter() - t0) * 1e9),
                engine=self._monitor_engine, mode="host",
                steps=n_steps, forwards=forwards, proposed=proposed,
                accepted=accepted, emitted=total,
                host_syncs=forwards)
        return len(self._slot_req)

    # lint: hot-path
    def decode_segment(self, n_steps: int,
                       cfg: Optional[GenerationConfig] = None):
        """Run ``n_steps`` ragged decode steps over the current slots;
        collect per-request tokens and retire finished requests. Returns
        the number of still-active requests.

        Each request decodes under ITS OWN GenerationConfig (installed
        at ``add_request``) — including its seed, which every sampling
        step folds into the per-row noise key, so a request's sampled
        trajectory is a function of its own config, not of its
        batchmates. ``cfg`` is optional and only seeds the segment's
        SHARED base stream (back-compat with the one-config ``serve()``
        driver — omitted, the base stream is seeded from 0)."""
        if not self._slot_req:
            return 0
        if self._spec:
            # at least one live slot is speculating: the whole batch
            # rides ONE widened verify program (plain/sampled rows at
            # 1 token/step). Device mode fuses all n_steps into one
            # compiled segment; host mode drives the per-step loop
            # its host proposers need.
            if self.spec_mode == "device":
                return self._decode_segment_spec_device(n_steps, cfg)
            return self._decode_segment_spec(n_steps, cfg)
        n_live = len(self._slot_req)
        t0 = time.perf_counter()
        # every segment must draw fresh sampling noise even when no
        # request was admitted in between — fold in a segment counter
        self._segments_run += 1
        key = jax.random.fold_in(
            jax.random.PRNGKey(cfg.seed if cfg is not None else 0),
            self._segments_run)
        toks, self.last, self.lens, self.done_dev, self.caches = \
            self._segment_fn(n_steps)(
                self.params, self.last, self.lens, self.done_dev,
                self.active_dev, self.samp, self._bank(), self.caches,
                key)
        # lint: allow-host-sync(collection itself: ONE readback per
        # n_steps-step segment — tokens must reach handles/streams)
        toks = np.asarray(toks)
        # lint: allow-host-sync(same once-per-segment collection pull)
        done = np.asarray(self.done_dev)
        emitted = 0
        for slot, rid in list(self._slot_req.items()):
            rcfg = self._cfg[rid]
            take = min(self._budget[rid], n_steps)
            seq = toks[slot, :take].tolist()
            if (rcfg.eos_token_id is not None
                    and rcfg.eos_token_id in seq):
                seq = seq[:seq.index(rcfg.eos_token_id) + 1]
            self._tokens[rid].extend(int(t) for t in seq)
            self._budget[rid] -= len(seq)
            emitted += len(seq)
            if (self._budget[rid] <= 0 or bool(done[slot])
                    or len(seq) < take):
                self._retire(slot)
        if monitor.enabled():
            dt = time.perf_counter() - t0
            monitor.counter(
                "paddle_tpu_generated_tokens_total",
                "tokens generated by the continuous-batching engines "
                "(admission first-token + decode segments)").inc(emitted)
            self._tokens_per_sec_gauge().labels(
                engine=self._monitor_engine).set(
                emitted / dt if dt > 0 else 0.0)
        if trace.enabled():
            trace.record(
                "engine.segment",
                dur_ns=int((time.perf_counter() - t0) * 1e9),
                engine=self._monitor_engine, steps=n_steps,
                active=n_live, emitted=emitted)
        return len(self._slot_req)

    @staticmethod
    def _tokens_per_sec_gauge():
        return monitor.gauge(
            "paddle_tpu_decode_tokens_per_sec",
            "emitted tokens / wall time of the latest decode "
            "segment (includes host collect), per engine", ("engine",))

    def close(self):
        """Retire this engine's per-instance monitor series (idempotent;
        a dropped engine must not export its last tokens/sec forever)."""
        try:
            self._tokens_per_sec_gauge().remove(
                engine=self._monitor_engine)
        except Exception:
            pass
        # per-engine prefill series retire with the engine too, else a
        # dropped engine's label values accumulate in the registry (the
        # bucket dimension is open-ended, so retire by engine label)
        for name in ("paddle_tpu_prefill_requests_total",
                     "paddle_tpu_prefill_chunks_total",
                     "paddle_tpu_prefill_warmup_seconds",
                     "paddle_tpu_spec_draft_tokens_total"):
            try:
                monitor.remove_series(name, engine=self._monitor_engine)
            except Exception:
                pass
        # the program ledger rows this engine owned (prefill/chunk/
        # admit/segment/spec/quant/lora_install programs) and their
        # {program=...} series retire with it — same contract as the
        # per-engine series above
        try:
            from ..monitor import ledger

            ledger.release(self._monitor_engine)
        except Exception:
            pass
        reg = getattr(self, "adapters", None)   # __del__-safe: a
        if reg is not None:                     # half-built engine has
            reg.close()                         # no registry attr yet
        alloc = getattr(self, "alloc", None)
        if alloc is not None:
            alloc.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def collect_finished(self):
        out, self._finished = self._finished, {}
        return out

    # -- convenience driver -------------------------------------------------
    def grow_for_segment(self, n_steps: int):
        """Pre-segment capacity hook: grow every live request's cache
        coverage for the coming ``n_steps``-step segment and return the
        request ids that could NOT be covered (the caller must preempt
        victims before decoding). Dense slabs and reserved-mode paged
        pools pre-cover the worst case, so the base is a no-op; the
        paged engine's optimistic mode overrides it."""
        return []

    def serve(self, prompts, cfg: Optional[GenerationConfig] = None,
              segment_steps: int = 8):
        """Continuous-batching driver: admits requests as slots free up,
        decoding in fixed segments. Returns generated ids (prompt NOT
        included) in submission order.

        Under an optimistic-mode paged engine this driver handles KV
        memory pressure the same way the serving scheduler does: each
        inter-segment gap grows live mappings and, when the pool is
        dry, preempts the YOUNGEST of its own requests (never the
        oldest — forward progress) and re-queues ``prompt + generated``
        with the budget reduced, so a tight pool degrades to lower
        concurrency instead of raising away completed results (greedy
        resume is bitwise-identical to an unpreempted run). Only a
        request the pool cannot hold even alone still raises
        :class:`PagePoolExhausted` — the same workload would fail
        reserved-mode admission too."""
        cfg = cfg or GenerationConfig()
        pending = list(enumerate(prompts))
        cfgs = {}      # idx -> replay cfg (budget reduced); else ``cfg``
        prefix = {}    # idx -> tokens emitted before its preemption(s)
        order = {}
        results = {}
        foreign = {}   # requests admitted outside this serve() call

        def _settle(idx, seq):
            pre = prefix.pop(idx, None)
            results[idx] = (seq if pre is None else np.concatenate(
                [np.asarray(pre, np.int32), np.asarray(seq, np.int32)]))

        while len(results) < len(prompts):
            while pending and self._free:
                idx0, p0 = pending[0]
                if not self._can_admit(_prompt_len(p0),
                                       cfgs.get(idx0, cfg)):
                    if not self._slot_req:
                        # nothing active to drain: the request can NEVER
                        # fit — let add_request raise its loud error
                        idx, p = pending.pop(0)
                        order[self.add_request(
                            p, cfgs.get(idx, cfg))] = idx
                    break  # transient: defer to the next segment gap
                idx, p = pending.pop(0)
                order[self.add_request(p, cfgs.get(idx, cfg))] = idx
            # inter-segment gap: memory-pressure relief (see docstring)
            while True:
                short = self.grow_for_segment(segment_steps)
                if not short:
                    break
                ours = sorted(r for r in self._slot_req.values()
                              if r in order)
                if len(ours) < 2:
                    # our only (oldest-surviving) request, or a foreign
                    # row we must not touch: decode_segment's guard
                    # raises the loud typed error if it stays short
                    break
                toks = self.preempt_request(ours[-1])   # youngest
                idx = order.pop(ours[-1])
                pre = list(prefix.pop(idx, [])) + [int(t) for t in toks]
                # budget against the ORIGINAL cfg: ``pre`` is the full
                # emitted history, so measuring it against an earlier
                # replay's already-reduced max_new_tokens would
                # double-subtract the first preemption's prefix and
                # silently truncate a twice-preempted request
                remaining = cfg.max_new_tokens - len(pre)
                if remaining < 1 or (cfg.eos_token_id is not None
                                     and pre
                                     and pre[-1] == cfg.eos_token_id):
                    results[idx] = np.asarray(pre, np.int32)
                    continue    # already finished: nothing to replay
                prefix[idx] = pre
                kw = dict(vars(cfg))
                kw["max_new_tokens"] = remaining
                cfgs[idx] = GenerationConfig(**kw)
                # replays re-admit BEFORE new work (they held capacity
                # when pressure hit); greedy re-prefill of the same
                # prefix is bitwise-identical to the uninterrupted run
                pending.insert(0, (idx, np.concatenate(
                    [np.asarray(prompts[idx], np.int32).reshape(-1),
                     np.asarray(pre, np.int32)])))
            self.decode_segment(segment_steps, cfg)
            for rid, seq in self.collect_finished().items():
                if rid in order:
                    _settle(order.pop(rid), seq)
                else:
                    foreign[rid] = seq
        # foreign requests finished during our segments stay collectable
        self._finished.update(foreign)
        return [results[i] for i in range(len(prompts))]


class PagedContinuousBatchingEngine(ContinuousBatchingEngine):
    """ContinuousBatchingEngine over a PAGED KV pool (vLLM-style layout
    the reference's contiguous CacheKV slabs cannot express): cache
    slots are page-table rows into shared per-layer pools, so HBM holds
    ``num_pages * page_size`` tokens total — the tokens in flight — not
    ``max_batch * max_len``, and any free page serves any slot.

    Two ``admission_mode`` policies govern the page pool:

    - ``"reserved"`` (default): admission RESERVES a request's worst
      case (prompt + max_new_tokens, capped at max_len) so a running
      request can never exhaust the pool mid-decode — safe, but
      concurrency is capped by the worst case while most requests
      finish early on EOS;
    - ``"optimistic"`` (vLLM-style, Kwon et al. SOSP'23): admission
      claims only the prompt's pages plus ONE page of headroom, and
      the engine grows each live slot's mapping per inter-segment gap
      (:meth:`grow_for_segment`, capped by the request's remaining
      budget). When growth cannot be satisfied the CALLER must relieve
      pressure — :meth:`preempt_request` reclaims a victim's slot and
      pages exactly like ``cancel_request`` and returns its partial
      tokens for replay (the serving scheduler parks the handle on its
      replay list; greedy preempt-resume is bitwise-identical to an
      unpreempted run). ``decode_segment`` re-checks growth and raises
      :class:`PagePoolExhausted` if pressure was left unhandled —
      never a silent dropped write. ``kv_watermark`` (fraction of the
      pool, optimistic mode only) pauses NEW admissions while the pool
      is already under pressure, so preemption is the fallback, not
      the steady state.

    ``prefix_cache=True`` turns on AUTOMATIC PREFIX CACHING (vLLM-style
    content-addressable pages; RadixAttention generalizes the same
    reuse to a tree): admission hashes the prompt in page_size-token
    blocks, maps already-resident blocks READ-ONLY into the new slot's
    page table (refcount++ — prefill and page claiming skip them; only
    the uncached tail runs through the bucketed/chunked prefill at a
    traced offset), and the first write into a shared page — a
    divergent suffix mid-block, or decode appending into a
    partially-filled shared tail page — goes through host-side
    COPY-ON-WRITE in the inter-segment gap: claim a fresh page, copy
    the pool rows, swap the table entry. Retirement decrements
    refcounts instead of freeing; fully-released cached pages park in
    an LRU free-but-indexed state the pool reclaims on demand, so
    cache capacity is whatever the pool isn't actively using. Shared
    pages (refcount > 1) are never preemption victims — preempting a
    request releases only ITS references. Warm-prefix admissions are
    bitwise-identical (greedy) to cold runs: the gathered prefix KV is
    the very KV the original prefill wrote, and the tail rides the
    same traced-offset program chunked admission already proves
    bitwise-equal to one-shot prefill.

    ``serve`` defers admission while the pool is transiently full and
    raises only for requests that could never fit. The page table
    lives host-side (numpy) and is shipped to the device once per
    segment. ``debug_pages=True`` runs the allocator's ``check()``
    invariant validator at every gap and after every page operation,
    plus a per-gap write-coverage assert (no live slot's length past
    its mapped pages, no imminent write into a shared page — the
    forgotten-CoW / silent-drop net). Requires the model to implement
    ``init_paged_cache`` / ``forward_decode_paged`` (llama does; see
    LlamaAttention.forward_decode_paged).
    """

    def __init__(self, model, max_batch: int, num_pages: int,
                 page_size: int, max_pages: int,
                 prefill_buckets="auto",
                 prefill_chunk: Optional[int] = None,
                 admission_mode: str = "reserved",
                 kv_watermark: float = 0.9,
                 debug_pages: bool = False,
                 prefix_cache: bool = False,
                 kv_dtype: str = "bf16",
                 draft_k: int = 0, ngram_max: int = 3,
                 spec_mode: str = "host", spec_draft: str = "ngram",
                 spec_history: int = 128,
                 lora_capacity: int = 0, lora_rank: int = 8,
                 lora_targets=("q", "k", "v", "o"),
                 tp_degree: int = 1, tp_devices=None):
        from ..quantization.kv import KV_DTYPES
        from .paged_cache import PageAllocator

        if admission_mode not in ADMISSION_MODES:
            raise ValueError(
                f"admission_mode must be one of {ADMISSION_MODES}, got "
                f"{admission_mode!r}")
        if not (isinstance(kv_watermark, (int, float))
                and 0 < kv_watermark <= 1):
            raise ValueError(
                f"kv_watermark must satisfy 0 < w <= 1 (fraction of "
                f"the page pool), got {kv_watermark!r}")
        if kv_dtype not in KV_DTYPES:
            raise ValueError(
                f"kv_dtype must be one of {KV_DTYPES}, got "
                f"{kv_dtype!r}")
        self.admission_mode = admission_mode
        self.kv_watermark = float(kv_watermark)
        self.prefix_cache = bool(prefix_cache)
        # overload-control actuator (serving.control brownout rung 4):
        # while True, NEW admissions skip prefix-cache lookup/insert
        # and take the plain cold path (already warmed — pausing
        # compiles nothing, and no CoW/shared pages are minted under
        # pressure). Resident cached blocks stay mapped; in-flight
        # warm admissions finish normally. Host bool, flipped by the
        # serving scheduler thread between segments.
        self.prefix_pause = False
        # KV page storage: "bf16" = the model's cache dtype, bitwise
        # the pre-quantization path; "int8" stores pages int8 with
        # per-(page, kv_head) running-absmax scales riding the page
        # table — half the bytes per decode read, ~2x the pages at
        # fixed HBM, correctness bar bounded-not-bitwise (see
        # quantization.kv). Must be set before the base __init__
        # builds the pools.
        self.kv_dtype = kv_dtype
        # slot -> warm-admission info ({"ids","c_map","hashes","saved"})
        # staged between the admission's prefill and its cache install;
        # popped by _install_mini / _abort_admit
        self._prefix_stash = {}
        # segment count a clean grow_for_segment covered; decode_segment
        # consumes it to skip its (device-syncing) exhaustion re-check
        self._growth_stamp: Optional[int] = None
        # (lens, done) host copies shared by every grow_for_segment call
        # in ONE gap — relief that preempts k victims re-runs the grow
        # loop k+1 times, but lens/done only change when a segment runs
        # (decode) or a slot admits (_register), both of which clear it
        self._gap_sync = None
        self.num_pages = num_pages
        self.page_size = page_size
        self.alloc = PageAllocator(num_pages, page_size, max_batch,
                                   max_pages, debug=debug_pages,
                                   prefix_cache=prefix_cache,
                                   kv_dtype=kv_dtype)
        super().__init__(model, max_batch,
                         max_len=max_pages * page_size,
                         prefill_buckets=prefill_buckets,
                         prefill_chunk=prefill_chunk,
                         draft_k=draft_k, ngram_max=ngram_max,
                         spec_mode=spec_mode, spec_draft=spec_draft,
                         spec_history=spec_history,
                         lora_capacity=lora_capacity,
                         lora_rank=lora_rank,
                         lora_targets=lora_targets,
                         tp_degree=tp_degree, tp_devices=tp_devices)
        self._measure_quant_savings()

        def reset_scales(pools, mask):
            # ONE fixed-shape program per pool shape: freshly claimed
            # pages' scale rows (a previous owner's absmax leftovers)
            # drop to the floor before any write — per-page dispatches
            # or a count-shaped index vector would recompile per gap
            from ..quantization.kv import KV_SCALE_FLOOR

            out = []
            for kp, vp, ks, vs in pools:
                ks = jnp.where(mask[:, None], KV_SCALE_FLOOR, ks)
                vs = jnp.where(mask[:, None], KV_SCALE_FLOOR, vs)
                out.append((kp, vp, ks, vs))
            return out

        self._reset_scales = monitor.monitored_jit(
            reset_scales, name="cb_reset_scales",
            owner=self._monitor_engine, donate_argnums=(0,))

    def _make_caches(self):
        # TP: pools (and int8 scales) shard on the kv-head axis; the
        # page TABLE replicates — page indices are mesh-invariant, so
        # the allocator/prefix-cache host logic needs no fork
        if self.kv_dtype == "int8":
            try:
                pools = self.model.init_paged_cache(
                    self.num_pages, self.page_size, kv_dtype="int8")
            except TypeError as e:
                raise ValueError(
                    f"kv_dtype='int8' needs a model whose "
                    f"init_paged_cache accepts kv_dtype (llama does); "
                    f"{type(self.model).__name__} does not") from e
            return (self._tp_kv(pools),
                    self._tp_rep(jnp.asarray(self.alloc.page_table)))
        return (self._tp_kv(self.model.init_paged_cache(
                    self.num_pages, self.page_size)),
                self._tp_rep(jnp.asarray(self.alloc.page_table)))

    def _measure_quant_savings(self) -> None:
        """Price the int8 layout from the REAL pool arrays: HBM bytes
        per page a bf16 pool would need minus what the int8 pools +
        scales actually take — the allocator counts it per claimed
        page (``paddle_tpu_kv_quant_bytes_saved_total``)."""
        if self.kv_dtype != "int8":
            self.alloc.bytes_saved_per_page = 0
            return
        pools, _ = self.caches
        base = quant = 0
        for kp, vp, ks, vs in pools:
            base += (kp.size + vp.size) * 2          # bf16 baseline
            quant += (kp.nbytes + vp.nbytes + ks.nbytes + vs.nbytes)
        self.alloc.bytes_saved_per_page = max(
            (base - quant) // self.num_pages, 0)

    def kv_page_cost(self) -> dict:
        """HBM cost of one page under the current storage dtype:
        ``{"bytes_per_page"}`` is the actual cost (scales included);
        ``{"bf16_equiv_bytes_per_page"}`` prices the SAME page at
        2 bytes/element — the production-baseline denominator for
        serve_bench's effective-capacity record, independent of the
        CPU test model's f32 cache dtype."""
        pools, _ = self.caches
        total = elems = 0
        for entry in pools:
            total += sum(a.nbytes for a in entry)
            elems += entry[0].size + entry[1].size
        return {"bytes_per_page": total // self.num_pages,
                "bf16_equiv_bytes_per_page":
                    2 * elems // self.num_pages}

    def set_kv_dtype(self, kv_dtype: str) -> None:
        """Swap the pool storage dtype on an IDLE engine (the
        ``Server(kv_dtype=...)`` mirror hook): rebuilds the pools —
        any cached prefix KV dies with them, so the content index
        clears too — and keeps every compiled program (the other
        dtype's variants stay cached; warmup covers the new ones)."""
        from ..quantization.kv import KV_DTYPES

        if kv_dtype not in KV_DTYPES:
            raise ValueError(
                f"kv_dtype must be one of {KV_DTYPES}, got "
                f"{kv_dtype!r}")
        if kv_dtype == self.kv_dtype:
            return
        if self._slot_req:
            raise RuntimeError(
                "kv_dtype can only be changed on an idle engine")
        # old pools dropped before the new ones allocate (reset_state's
        # peak-HBM argument applies here too)
        self.caches = None
        self.alloc.clear_prefix_index()
        self.alloc.set_kv_dtype(kv_dtype)
        self.kv_dtype = kv_dtype
        self._prefix_stash.clear()
        self._growth_stamp = None
        self._gap_sync = None
        self.caches = self._make_caches()
        self._measure_quant_savings()

    def _flush_fresh_scales(self) -> None:
        """Reset freshly claimed pages' scale rows to the floor (int8;
        one masked fixed-shape program) — runs at the write choke
        points (cache install, pre-segment) so no quantized store ever
        runs absmax against a previous owner's scales."""
        if self.kv_dtype != "int8":
            return
        fresh = self.alloc.take_fresh_scales()
        if not fresh:
            return
        mask = np.zeros((self.num_pages,), bool)
        mask[fresh] = True
        pools, pt = self.caches
        self.caches = (self._reset_scales(pools, jnp.asarray(mask)),
                       pt)

    def load(self) -> dict:
        out = super().load()
        out["kv_dtype"] = self.kv_dtype
        return out

    def export_kv_pages(self, tokens, salt: bytes = b"") -> dict:
        """Export the resident cached KV pages covering a prompt's
        longest FULL-BLOCK prefix: the read half of a cross-process
        page handoff (disaggregated prefill/decode). Returns a payload
        of chain-hashed blocks plus per-layer page rows — raw pool
        dtype (int8 rows ship with their per-page scales), so the
        transfer is a page COPY, never a format conversion.

        Must run on the scheduler thread in the inter-segment gap
        (``Server.export_kv`` marshals there): the pools are DONATED
        by device writes, so no other thread may read ``self.caches``.
        Partial-block tails never export — the importer parks blocks
        refcount-0 with no CoW discipline attached, so only token-
        complete, hash-verified pages are safe to ship."""
        from .paged_cache import _chain_root

        ids = np.ascontiguousarray(
            np.asarray(tokens).reshape(-1), np.int32)
        pids, cov, hashes = self.alloc.lookup_prefix(ids, salt=salt)
        ps = self.page_size
        nfull = min(len(pids), cov // ps, len(hashes))
        pids = pids[:nfull]
        root = _chain_root(salt)
        blocks = []
        for b in range(nfull):
            blocks.append({
                "hash": hashes[b].hex(),
                "parent": (hashes[b - 1] if b else root).hex(),
                "tokens": ids[b * ps:(b + 1) * ps].tolist()})
        pools, _pt = self.caches
        idx = np.asarray(pids, np.int32)
        layers = []
        for pool in pools:
            if self.kv_dtype == "int8":
                kp, vp, ks, vs = pool
                layers.append({"k": np.asarray(kp[idx]),
                               "v": np.asarray(vp[idx]),
                               "k_scale": np.asarray(ks[idx]),
                               "v_scale": np.asarray(vs[idx])})
            else:
                kp, vp = pool
                layers.append({"k": np.asarray(kp[idx]),
                               "v": np.asarray(vp[idx])})
        return {"version": 1, "kv_dtype": self.kv_dtype,
                "page_size": ps, "salt": salt.hex(),
                "coverage": nfull * ps, "blocks": blocks,
                "layers": layers}

    def import_kv_pages(self, payload: dict) -> dict:
        """Install exported KV pages into this engine's pools and
        prefix index: the write half of the cross-process handoff.
        Every block re-derives its chain hash from (parent, tokens)
        before adoption — a corrupted or mis-framed page can never
        enter the content index — and an already-resident hash is a
        dedup no-op (``PageAllocator.adopt_block``), which makes a
        replayed handoff idempotent. Imported pages PARK (refcount 0,
        LRU-reclaimable): the next admission of the matching prompt
        warm-hits them read-only through the ordinary prefix-cache
        path. Same gap-only threading contract as
        :meth:`export_kv_pages`. Returns
        ``{"imported", "deduped", "coverage"}``."""
        from .paged_cache import (_block_hash, install_page,
                                  install_page_q)

        if payload.get("kv_dtype") != self.kv_dtype:
            raise ValueError(
                f"kv_dtype mismatch: payload "
                f"{payload.get('kv_dtype')!r} vs engine "
                f"{self.kv_dtype!r} — KV handoff is a page copy, "
                f"never a format conversion")
        if int(payload.get("page_size", -1)) != self.page_size:
            raise ValueError(
                f"page_size mismatch: payload "
                f"{payload.get('page_size')} vs engine "
                f"{self.page_size}")
        pools, _pt = self.caches
        layers = payload.get("layers") or []
        if len(layers) != len(pools):
            raise ValueError(
                f"layer count mismatch: payload {len(layers)} vs "
                f"engine {len(pools)}")
        blocks = payload.get("blocks") or []
        kp0 = pools[0][0]
        for lay in layers:
            for key in (("k", "v", "k_scale", "v_scale")
                        if self.kv_dtype == "int8" else ("k", "v")):
                arr = lay.get(key)
                if arr is None or len(arr) != len(blocks):
                    raise ValueError(
                        f"payload layer missing/short {key!r} rows")
            if (tuple(lay["k"].shape[1:]) != tuple(kp0.shape[1:])
                    or lay["k"].dtype != kp0.dtype):
                raise ValueError(
                    f"page geometry mismatch: payload "
                    f"{lay['k'].dtype}{lay['k'].shape[1:]} vs pool "
                    f"{kp0.dtype}{tuple(kp0.shape[1:])}")
        imported = deduped = 0
        for b, blk in enumerate(blocks):
            h = bytes.fromhex(blk["hash"])
            parent = bytes.fromhex(blk["parent"])
            toks = np.ascontiguousarray(
                np.asarray(blk["tokens"]).reshape(-1), np.int32)
            if _block_hash(parent, toks) != h:
                raise ValueError(
                    f"block {b}: chain hash does not match "
                    f"(parent, tokens) — corrupted handoff rejected")
            pid = self.alloc.adopt_block(h, parent, toks)
            if pid is None:
                deduped += 1
                continue
            pools, pt = self.caches
            new_pools = []
            if self.kv_dtype == "int8":
                for (kp, vp, ks, vs), lay in zip(pools, layers):
                    kp, vp, ks, vs = install_page_q(
                        kp, vp, ks, vs, jnp.int32(pid),
                        lay["k"][b], lay["v"][b],
                        lay["k_scale"][b], lay["v_scale"][b])
                    new_pools.append((kp, vp, ks, vs))
                self.caches = (new_pools, pt)
                self.alloc.note_scale_copied(pid)
            else:
                for (kp, vp), lay in zip(pools, layers):
                    kp, vp = install_page(kp, vp, jnp.int32(pid),
                                          lay["k"][b], lay["v"][b])
                    new_pools.append((kp, vp))
                self.caches = (new_pools, pt)
            imported += 1
        return {"imported": imported, "deduped": deduped,
                "coverage": len(blocks) * self.page_size}

    def _fwd_ragged(self, params, tok, caches, lens, live, lora=None):
        from ..core.autograd import no_grad

        pools, pt = caches
        with substituted_state(self.model, params), no_grad():
            logits, pools = self.model.forward_decode_paged(
                Tensor(tok), pools, pt, lens, live,
                **self._fwd_kwargs(lora))
        return (logits.value if isinstance(logits, Tensor) else logits,
                (pools, pt))

    def _fwd_spec(self, params, inp, caches, lens, live, lora=None):
        from ..core.autograd import no_grad

        pools, pt = caches
        with substituted_state(self.model, params), no_grad():
            logits, pools, aux = \
                self.model.forward_decode_spec_paged(
                    Tensor(inp), pools, pt, lens, live,
                    **self._fwd_kwargs(lora))
        return (logits.value if isinstance(logits, Tensor) else logits,
                (pools, pt), aux)

    def _coverage_limit(self, slot: int) -> int:
        # the spec step may only ACCEPT tokens whose KV writes landed
        # in mapped pages — cap each row's acceptance at its grown
        # coverage (writes past it are dropped by the sentinel)
        return min(self.alloc.covered_tokens(slot), self.max_len)

    def _reserved(self, plen: int, cfg) -> int:
        return min(plen + cfg.max_new_tokens, self.max_len)

    def _optimistic_claim(self, plen: int, cfg) -> int:
        """Tokens an OPTIMISTIC admission claims up front: the prompt
        plus one page of headroom (the first decode step writes at
        position ``plen``, so bare-prompt coverage would force growth
        before the very first segment), never more than the worst case
        the reserved policy would take."""
        return min(plen + self.page_size, self._reserved(plen, cfg))

    def _can_admit(self, prompt_len: int, cfg) -> bool:
        # any free slot owns zero pages, so capacity is slot-agnostic.
        # Prefix caching never tightens this probe: a warm admission
        # claims at most what a cold one would (shared pages count as
        # coverage), and when the pool cannot also spare the one
        # copy-on-write page a partial-block hit needs, admission
        # DEGRADES the hit to full blocks instead of demanding more
        # (so a request whose worst case exactly fills the pool still
        # admits). can_admit saying yes must mean add_request cannot
        # raise for capacity.
        probe = self._free[0] if self._free else 0
        if self.admission_mode == "reserved":
            return self.alloc.can_fit(probe,
                                      self._reserved(prompt_len, cfg))
        claim = self._optimistic_claim(prompt_len, cfg)
        if not self.alloc.can_fit(probe, claim):
            return False
        if self._slot_req:
            # high watermark: while running requests already crowd the
            # pool, pause NEW admissions before growth pressure forces
            # a preemption — running work frees pages by finishing. An
            # IDLE pool skips the watermark (a lone request must always
            # be able to admit, or a big claim could wedge forever).
            used_after = (self.alloc.used_pages
                          + self.alloc.pages_for(claim))
            if used_after > self.kv_watermark * self.num_pages:
                return False
        return True

    def _lookup_degraded(self, slot: int, ids, plen: int, cfg):
        """Shared warm-admission preamble (one-shot AND chunked):
        longest resident cached prefix — in the admission's ADAPTER
        namespace (the chain hash is salted with the adapter id, so a
        base-model block can never warm-hit an adapter's admission or
        vice versa) — degraded to full blocks when the pool cannot
        spare the partial page's CoW."""
        salt = self._adapter_salt(slot)
        pids, c_map, hashes = self.alloc.lookup_prefix(ids[0],
                                                       salt=salt)
        pids, c_map = self._degrade_partial_hit(slot, plen, cfg,
                                                pids, c_map)
        return pids, c_map, hashes, salt

    def _admit_cache(self, slot: int, ids, plen: int, cfg):
        if self.prefix_cache and not self.prefix_pause:
            pids, c_map, hashes, salt = self._lookup_degraded(
                slot, ids, plen, cfg)
            self._prefix_stash[slot] = {
                "ids": ids, "c_map": c_map, "hashes": hashes,
                "saved": min(c_map, plen - 1), "salt": salt}
            if c_map > 0:
                return self._admit_cache_warm(slot, ids, plen, cfg,
                                              pids, c_map)
        # COLD path: prefill into a dense mini cache sized to the
        # prompt's BUCKET (no max_len slab — the pool is the whole
        # point; the bucket keys the compiled program count to
        # O(len(buckets))), then scatter the prompt's KV rows into
        # freshly reserved pages
        mini = self._mini_cache(self._prefill_width(plen))
        last_logits, mini = self._run_prefill(
            ids, plen, mini, aidx=self._aidx_stash.get(slot, 0))
        self._reserve_admit(slot, plen, cfg)
        self._install_mini(slot, mini, plen)
        return last_logits

    def _degrade_partial_hit(self, slot: int, plen: int, cfg, pids,
                             c_map: int):
        """A partial-block hit (coverage ending mid-page) maps a page
        the request must copy-on-write before its first write — one
        page BEYOND its normal claim. When the pool cannot spare it,
        DEGRADE the hit to full blocks (drop the partial page) rather
        than demand extra capacity: a request whose worst case exactly
        fills the pool must still admit, cache or no cache."""
        ps = self.page_size
        if not pids or c_map % ps == 0:
            return pids, c_map
        claim = (self._reserved(plen, cfg)
                 if self.admission_mode == "reserved"
                 else self._optimistic_claim(plen, cfg))
        if self.alloc.can_fit(slot, claim + ps):
            return pids, c_map
        return pids[:-1], (c_map // ps) * ps

    def _admit_cache_warm(self, slot: int, ids, plen: int, cfg, pids,
                          c_map: int):
        """Prefix-cache hit admission: gather the cached prefix KV from
        the resident pages (a pure copy — bitwise what the original
        prefill wrote), prefill ONLY the uncached tail at a traced
        offset through the shared chunk program, then map the cached
        pages read-only and install the tail. At least the LAST prompt
        token always recomputes — its logits seed the first sampled
        token — even when the whole prompt is resident (its KV write
        is simply masked out then)."""
        # compute start: everything below is served from cache; cap at
        # plen-1 so the last position's logits exist
        c_cmp = min(c_map, plen - 1)
        wt = (plen - c_cmp if self.prefill_buckets is None
              else _bucket_for(self.prefill_buckets, plen - c_cmp))
        # the tail chunk writes mini rows [c_cmp, c_cmp+wt) — pull the
        # compute start DOWN when the bucket would overhang max_len
        # (the fwd's dynamic_update_slice clamps, which would corrupt
        # cached rows); recomputing a few extra cached positions is
        # value-neutral (their installs are masked out) and keeps the
        # program keyed on wt alone
        c_cmp = min(c_cmp, self.max_len - wt)
        # tokens-saved is the compute actually skipped ([0, c_cmp)),
        # not the raw coverage — the clamp above shrinks it
        self._prefix_stash[slot]["saved"] = c_cmp
        tail = plen - c_cmp
        mini = self._mini_cache(self.max_len)
        mini = self._gather_mini(mini, pids)
        self._count_prefill("warm")
        if trace.enabled():
            trace.event("engine.prefill", engine=self._monitor_engine,
                        plen=plen, bucket="warm", cached=c_cmp)
        tail_ids = _pad_ids(ids[:, c_cmp:], wt)
        last_logits, mini = self._prefill_chunk(
            self.params, tail_ids, mini, jnp.int32(c_cmp),
            jnp.int32(tail - 1), self._bank(),
            jnp.int32(self._aidx_stash.get(slot, 0)))
        self.alloc.map_shared(slot, pids)
        self._reserve_admit(slot, plen, cfg)
        self._install_mini(slot, mini, plen)
        return last_logits

    def _gather_mini(self, mini, pids):
        """Copy the resident pages into the head of a max_len-width
        dense mini cache (per layer) — the cached-prefix KV the tail
        prefill attends over. The page vector is padded to the FULL
        page-table row width so every warm admission shares one
        compiled gather program (junk rows for the ``-1`` tail sit
        past the cached coverage, overwritten or masked)."""
        from .paged_cache import gather_pages, gather_pages_q

        row = np.full((self.alloc.page_table.shape[1],), -1, np.int32)
        row[:len(pids)] = pids
        pages = jnp.asarray(row)
        pools, _ = self.caches
        out = []
        if self.kv_dtype == "int8":
            # dequantize whole resident pages into the float mini: the
            # tail prefill attends over exactly the values the fused
            # decode reads see, so warm and cold agree to quantization
            # error, never to a format skew
            for (kp, vp, ks, vs), (mk, mv) in zip(pools, mini):
                mk, mv = gather_pages_q(kp, vp, ks, vs, pages, mk, mv)
                out.append((mk, mv))
            return out
        for (kp, vp), (mk, mv) in zip(pools, mini):
            mk, mv = gather_pages(kp, vp, pages, mk, mv)
            out.append((mk, mv))
        return out

    def _cow_page(self, slot: int, page_idx: int) -> None:
        """Host-side copy-on-write of one shared page in the
        inter-segment gap: claim a fresh page (allocator bookkeeping),
        copy the pool rows on device, swap the table entry (shipped at
        the next segment)."""
        from .paged_cache import copy_page, copy_page_q

        old, new = self.alloc.cow(slot, page_idx)
        pools, pt = self.caches
        new_pools = []
        if self.kv_dtype == "int8":
            # the copy carries the page's SCALES with its rows (int8
            # rows are meaningless under another page's scale); the
            # note tells the allocator's scale accounting the copy
            # happened — forgetting either fails check() loudly
            for kp, vp, ks, vs in pools:
                kp, vp, ks, vs = copy_page_q(kp, vp, ks, vs,
                                             jnp.int32(old),
                                             jnp.int32(new))
                new_pools.append((kp, vp, ks, vs))
            self.caches = (new_pools, pt)
            self.alloc.note_scale_copied(new)
            return
        for kp, vp in pools:
            kp, vp = copy_page(kp, vp, jnp.int32(old), jnp.int32(new))
            new_pools.append((kp, vp))
        self.caches = (new_pools, pt)

    def _reserve_admit(self, slot: int, plen: int, cfg) -> None:
        self.alloc.ensure(
            slot, self._reserved(plen, cfg)
            if self.admission_mode == "reserved"
            else self._optimistic_claim(plen, cfg))

    def _install_mini(self, slot: int, mini, plen: int) -> None:
        from .paged_cache import write_tokens, write_tokens_q

        # int8: reset freshly claimed pages' scale rows BEFORE the
        # quantized install runs its running absmax against them
        self._flush_fresh_scales()
        info = (self._prefix_stash.pop(slot, None)
                if self.prefix_cache else None)
        if info is not None and info["c_map"] > 0:
            self._install_mini_warm(slot, mini, plen, info)
        else:
            # COLD scatter: bucket-width rows (fixed shapes per bucket
            # — the scatter program count stays O(len(buckets)), not
            # O(#plens)): rows past plen land on reserved-but-unwritten
            # positions the decode mask hides and decode writes
            # overwrite, or on unmapped pages where write_tokens drops
            # them
            width = min(self._prefill_width(plen), mini[0][0].shape[1])
            pt = self._tp_rep(jnp.asarray(self.alloc.page_table))
            slots_v = jnp.full((width,), slot, jnp.int32)
            pos_v = jnp.arange(width, dtype=jnp.int32)
            pools, _ = self.caches
            new_pools = []
            if self.kv_dtype == "int8":
                # limit=plen: the pad tail past the prompt DROPS
                # instead of ratcheting headroom pages' running absmax
                # (their floor-reset scales already read stale rows
                # as ~0)
                for (kp, vp, ks, vs), (mk, mv) in zip(pools, mini):
                    kp, vp, ks, vs = write_tokens_q(
                        kp, vp, ks, vs, pt, slots_v, pos_v,
                        mk[0, :width], mv[0, :width],
                        limit=jnp.int32(plen))
                    new_pools.append((kp, vp, ks, vs))
            else:
                for (kp, vp), (mk, mv) in zip(pools, mini):
                    kp, vp = write_tokens(kp, vp, pt, slots_v, pos_v,
                                          mk[0, :width], mv[0, :width])
                    new_pools.append((kp, vp))
            self.caches = (new_pools, pt)
        if info is not None:
            # a cold admission POPULATES the cache; a warm one extends
            # it — either way the prompt's fully-written private blocks
            # become future hits (in the admission's adapter namespace)
            ps = self.page_size
            self.alloc.register_blocks(
                slot, info["hashes"], info["ids"][0],
                info["c_map"] // ps, plen // ps,
                salt=info.get("salt", b""))
            if info["c_map"] > 0:
                self.alloc.count_prefix_hit(info["saved"])

    def _install_mini_warm(self, slot: int, mini, plen: int,
                           info) -> None:
        """Install a warm admission's UNCACHED suffix: copy-on-write
        the shared page the first write would land in (divergent
        suffix mid-block — or, fully-cached prompts, the partial tail
        page decode will append into), then scatter exactly the rows
        ``[c_map, plen)``. Shared pages are never written: positions
        below the cached coverage are masked out of the scatter, and
        the garbage tail past ``plen`` lands only in private headroom
        pages or drops on unmapped ones."""
        from .paged_cache import scatter_rows, scatter_rows_q

        ps = self.page_size
        c_map = info["c_map"]
        # first position this slot will EVER write: the uncached
        # suffix's start, or (fully cached) decode's first append
        p0 = c_map if c_map < plen else plen
        if p0 % ps and self.alloc.needs_cow(slot, p0):
            self._cow_page(slot, p0 // ps)
        pt = self._tp_rep(jnp.asarray(self.alloc.page_table))
        if c_map < plen:
            mini_len = mini[0][0].shape[1]
            width = (plen - c_map if self.prefill_buckets is None
                     else _bucket_for(self.prefill_buckets,
                                      plen - c_map))
            width = min(width, mini_len)
            pools, _ = self.caches
            new_pools = []
            if self.kv_dtype == "int8":
                # masked-out rows drop from the quantized scatter too,
                # so shared read-only pages keep rows AND scales; the
                # CoW'd partial page's copied scales seed the running
                # absmax for the suffix rows landing in it
                for (kp, vp, ks, vs), (mk, mv) in zip(pools, mini):
                    kp, vp, ks, vs = scatter_rows_q(
                        kp, vp, ks, vs, pt, jnp.int32(slot),
                        jnp.int32(c_map), jnp.int32(plen), mk, mv,
                        width=width)
                    new_pools.append((kp, vp, ks, vs))
            else:
                for (kp, vp), (mk, mv) in zip(pools, mini):
                    kp, vp = scatter_rows(
                        kp, vp, pt, jnp.int32(slot), jnp.int32(c_map),
                        jnp.int32(plen), mk, mv, width=width)
                    new_pools.append((kp, vp))
            self.caches = (new_pools, pt)
        else:
            pools, _ = self.caches
            self.caches = (pools, pt)

    def _warmup_mini(self, width: int):
        return self._mini_cache(width)

    def _begin_admit_cache(self, slot: int, ids, plen: int, cfg):
        if not self.prefix_cache or self.prefix_pause:
            return super()._begin_admit_cache(slot, ids, plen, cfg)
        pids, c_map, hashes, salt = self._lookup_degraded(slot, ids,
                                                          plen, cfg)
        C = self.prefill_chunk
        # chunk windows must stay C-aligned (an overhanging window
        # would clamp and corrupt earlier KV), so the cursor starts at
        # the cached coverage aligned DOWN — the [start, c_map) sliver
        # recomputes but its writes are masked out at install
        start = (min(c_map, plen - 1) // C) * C
        self._prefix_stash[slot] = {"ids": ids, "c_map": c_map,
                                    "hashes": hashes, "saved": start,
                                    "salt": salt}
        self.alloc.map_shared(slot, pids)
        self._reserve_admit(slot, plen, cfg)
        # copy-on-write the partial shared page EAGERLY, while the
        # claim is atomic with the reservation — install runs gaps
        # later, and the spare page must not be stolen by growth or
        # another admission in between
        p0 = c_map if c_map < plen else plen
        if p0 % self.page_size and self.alloc.needs_cow(slot, p0):
            self._cow_page(slot, p0 // self.page_size)
        mini = self._mini_cache(self.max_len)
        if pids:
            # full cached coverage gathered (fixed-shape program);
            # rows the chunks recompute from `start` just overwrite
            # their gathered copies with bitwise-identical values
            mini = self._gather_mini(mini, pids)
        return mini, start

    def _warmup_prefix(self) -> dict:
        """Pre-compile every program a WARM admission can hit — the
        page gather, the CoW page copy, and one tail-prefill + masked
        scatter per prefill bucket — so the first cache hit never pays
        an XLA compile inside the latency-critical gap. All calls are
        value-neutral: nothing is mapped, every scatter row is masked
        out (limit 0), and the page-0 self-copy happens before any
        request owns it. Under int8 the fresh-scale flush program
        warms here too (all-False mask — a no-op write)."""
        out = {}
        if self.kv_dtype == "int8":
            t0 = time.perf_counter()
            pools, pt = self.caches
            self.caches = (self._reset_scales(
                pools, jnp.zeros((self.num_pages,), bool)), pt)
            out["reset_scales"] = time.perf_counter() - t0
        if not self.prefix_cache:
            return out
        from .paged_cache import (copy_page, copy_page_q, scatter_rows,
                                  scatter_rows_q)

        quant = self.kv_dtype == "int8"
        t0 = time.perf_counter()
        mini = self._gather_mini(self._mini_cache(self.max_len), [])
        pools, pt = self.caches
        new_pools = []
        for entry in pools:
            if quant:
                new_pools.append(copy_page_q(*entry, jnp.int32(0),
                                             jnp.int32(0)))
            else:
                new_pools.append(copy_page(*entry, jnp.int32(0),
                                           jnp.int32(0)))
        self.caches = (new_pools, pt)
        out["prefix_gather_copy"] = time.perf_counter() - t0
        pt_dev = self._tp_rep(jnp.asarray(self.alloc.page_table))
        for w in (self.prefill_buckets or ()):
            t0 = time.perf_counter()
            _, mini = self._prefill_chunk(
                self.params, np.zeros((1, w), np.int32), mini,
                jnp.int32(0), jnp.int32(0), self._bank(),
                jnp.int32(0))
            pools, _ = self.caches
            new_pools = []
            for entry, (mk, mv) in zip(pools, mini):
                if quant:
                    new_pools.append(scatter_rows_q(
                        *entry, pt_dev, jnp.int32(0), jnp.int32(0),
                        jnp.int32(0), mk, mv, width=w))
                else:
                    new_pools.append(scatter_rows(
                        *entry, pt_dev, jnp.int32(0), jnp.int32(0),
                        jnp.int32(0), mk, mv, width=w))
            self.caches = (new_pools, pt)
            out[f"prefix_warm_{w}"] = time.perf_counter() - t0
        return out

    def _abort_admit(self, slot: int) -> None:
        super()._abort_admit(slot)
        self._prefix_stash.pop(slot, None)
        self.alloc.free_slot(slot)   # release any reserved pages

    def _register(self, slot: int, rid: int, first, tok_done, cfg,
                  t0: float) -> int:
        # a new live slot may be under-covered for the next segment
        # (optimistic claims stop at prompt + one page) — any growth
        # stamp predating it is stale, as is the gap's (lens, done)
        # snapshot (admission just wrote this slot's rows). Retire/free
        # paths only RELEASE capacity and never un-cover or advance a
        # surviving slot, so they keep both.
        self._growth_stamp = None
        self._gap_sync = None
        return super()._register(slot, rid, first, tok_done, cfg, t0)

    def _retire(self, slot, event: str = "finished"):
        super()._retire(slot, event)
        self.alloc.free_slot(slot)

    def reset_state(self) -> None:
        # every slot's pages go back to the pool BEFORE the base rebuild
        # reads alloc.page_table into the fresh cache tuple — a restart
        # must leave zero pages leaked no matter what the fault
        # interrupted
        for slot in range(self.max_batch):
            self.alloc.free_slot(slot)
        # the pools are rebuilt from zeros below: every cached block's
        # KV is gone, so the content index must go with it (parked
        # pages return to the free heap)
        self.alloc.clear_prefix_index()
        # the fresh pools below start at floor scales: pending resets
        # refer to arrays about to be dropped
        self.alloc.take_fresh_scales()
        self._prefix_stash.clear()
        self._growth_stamp = None
        self._gap_sync = None
        super().reset_state()

    # -- optimistic-mode memory pressure (host-side, between segments) -------
    def grow_for_segment(self, n_steps: int):  # lint: hot-path
        """Grow every live slot's page mapping to cover the coming
        ``n_steps``-step decode segment (optimistic mode; a no-op in
        reserved mode, where admission pre-claimed the worst case).
        Returns the request ids whose growth could NOT be satisfied —
        the pool is dry and the caller must preempt victims (or accept
        :class:`PagePoolExhausted` from ``decode_segment``).

        OLDEST request first (ascending rid — admission order), so
        pressure always lands on the youngest work: combined with a
        scheduler that never preempts the oldest survivor, the head of
        the line always makes forward progress and pressure can never
        deadlock the loop. A row's target is capped by its remaining
        budget: a segment emits at most ``min(n_steps, budget)`` kept
        tokens, whose last cache write lands at position
        ``len + min(n_steps, budget) - 1`` — device steps past the
        budget write into (and read from) uncovered positions, but
        every token they produce is discarded host-side at collection,
        so capping is safe and saves pages. NO partial growth: a slot
        either covers the full target or joins the short list —
        partially covered steps would emit garbage tokens the host
        KEEPS."""
        if self.admission_mode != "optimistic" or not self._slot_req:
            return []
        if self._gap_sync is None:
            # lint: allow-host-sync(ONE cached lens/done pull per gap —
            # growth decisions need real lengths; decode_segment's
            # re-check reuses this exact pull via _gap_sync)
            self._gap_sync = (np.asarray(self.lens),
                              np.asarray(self.done_dev))
        lens, done = self._gap_sync
        short = []
        for slot, rid in sorted(self._slot_req.items(),
                                key=lambda kv: kv[1]):
            if bool(done[slot]):
                continue       # frozen rows never write
            # a SPECULATING row can accept up to spec_k+1 tokens per
            # verify step, so its per-segment growth target scales by
            # its window width (still budget-capped: acceptance never
            # outruns the tokens the host will keep). Draft-scratch
            # writes past the target drop harmlessly — the spec step
            # caps acceptance at the grown coverage.
            w = self._spec_k_of(rid) + 1
            target = min(int(lens[slot])
                         + min(n_steps * w, self._budget[rid]),
                         self.max_len)
            if self.alloc.can_fit(slot, target):
                self.alloc.ensure(slot, target)
            else:
                short.append(rid)
        # a clean pass covers the coming segment: decode_segment(n_steps)
        # may skip its re-check until the slot set changes (_register) or
        # the segment runs (lens advance)
        self._growth_stamp = n_steps if not short else None
        if short and trace.enabled():
            # ENGINE rids (not serving trace keys): the pool could not
            # cover these rows' growth — the preemptions that follow in
            # the flight ring are this event's consequence
            trace.event("engine.grow_short",
                        engine=self._monitor_engine,
                        engine_rids=tuple(short),
                        free_pages=self.alloc.free_pages)
        return short

    def preempt_request(self, rid: int, reason: str = "pressure"):
        """Preempt an ACTIVE request under memory pressure: reclaim its
        slot AND pages immediately (mirroring ``cancel_request``'s
        reclaim) and return the partial tokens generated so far
        (np.int32) — the caller owns parking them and replaying
        ``prompt + tokens`` through normal admission later (greedy
        replay is bitwise-identical to an unpreempted run; see the
        serving scheduler's replay machinery). Returns None when
        ``rid`` is not active. The request never appears in
        ``collect_finished()``; the retirement event and the pool's
        ``paddle_tpu_kv_preemptions_total{reason}`` counter record it.

        Like ``cancel_request``: call only from the thread driving the
        engine, BETWEEN decode segments."""
        out = self._evict_active(rid, "preempted")
        if out is not None:
            self.alloc.count_preemption(reason)
        return out

    # lint: hot-path
    def decode_segment(self, n_steps: int,
                       cfg: Optional[GenerationConfig] = None):
        if not self._slot_req:
            return 0
        if self.admission_mode == "optimistic":
            # final guard: a driver that skipped pressure relief must
            # fail LOUDLY here, not let write_tokens silently drop KV
            # writes past the mapped range and corrupt the request's
            # decode. When the scheduler's gap already ran a clean
            # grow_for_segment(n_steps) (stamp matches, slot set
            # unchanged since), the re-check — two blocking device
            # fetches + an O(active) allocator pass — is skipped; the
            # stamp is single-shot because this segment advances lens
            short = ([] if self._growth_stamp == n_steps
                     else self.grow_for_segment(n_steps))
            self._growth_stamp = None
            self._gap_sync = None    # the segment advances lens/done
            if short:
                raise PagePoolExhausted(
                    short,
                    f"page pool exhausted in the inter-segment gap: "
                    f"requests {short} cannot grow for the next "
                    f"{n_steps}-step segment "
                    f"({self.alloc.available_pages} pages reclaimable) "
                    f"— preempt victims (preempt_request) or grow "
                    f"num_pages")
        # int8: pages the gap claimed (growth, reserves) get their
        # scale rows floored before this segment's quantized writes
        self._flush_fresh_scales()
        # reserved mode: admission reserved every running request's
        # worst case, so no growth can fail — just ship the table
        if self.alloc.debug:
            self.alloc.check()
            if self.kv_dtype == "int8":
                # device half of the scale invariants: every live
                # page's scales finite and positive (layer 0 stands
                # for all layers — one program writes them all)
                pools, _ = self.caches
                self.alloc.check_scales(pools[0][2], pools[0][3])
            # write_tokens drops out-of-mapping writes SILENTLY (one
            # compiled program) and a forgotten copy-on-write would
            # mutate a shared page other requests read — both surface
            # as wrong tokens far downstream. Under debug_pages the gap
            # re-asserts, per live slot, that the live length is inside
            # the mapped pages and the imminent write lands in a
            # private page.
            # lint: allow-host-sync(debug_pages-only invariant check —
            # never on the production path; the pull is the price of
            # validating coverage before a silent-drop write)
            lens = np.asarray(self.lens)
            # lint: allow-host-sync(same debug_pages-only pull)
            done = np.asarray(self.done_dev)
            for slot, rid in self._slot_req.items():
                if bool(done[slot]):
                    continue
                # a speculating row's imminent writes span its whole
                # draft window, not just the next position — the
                # shared-page (missing-CoW) net must cover all of it
                self.alloc.check_coverage(
                    slot, int(lens[slot]),
                    write_ahead=1 + self._spec_k_of(rid))
        pools, _ = self.caches
        self.caches = (pools,
                       self._tp_rep(jnp.asarray(self.alloc.page_table)))
        return super().decode_segment(n_steps, cfg)
