"""Autoregressive generation engine over the KV-cache decode path.

The serving counterpart of the reference's fused_multi_transformer decode
loop (``fused_multi_transformer_op.cu.h:745`` masked MHA over CacheKV; the
reference drives it token-by-token from AnalysisPredictor). TPU-native
form: ONE jitted prefill program + ONE jitted multi-token decode program
(``lax.scan`` over steps, cache carried functionally, cache buffers
donated) — token steps never leave the device, so the host round-trip
(65ms through a tunnel, ~1ms locally) is paid once per generate() call,
not once per token.
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.functional_call import substituted_state

__all__ = ["GenerationConfig", "CausalLMEngine"]


class GenerationConfig:
    def __init__(self, max_new_tokens: int = 64, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0, do_sample: bool = False,
                 eos_token_id: Optional[int] = None, seed: int = 0):
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.do_sample = do_sample
        self.eos_token_id = eos_token_id
        self.seed = seed


def _sample(logits, key, cfg: GenerationConfig):
    """One next-token choice from [B, V] logits."""
    if not cfg.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / max(cfg.temperature, 1e-6)
    if cfg.top_k and cfg.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -cfg.top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if cfg.top_p < 1.0:
        sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_l, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p; cutoff = last kept logit
        keep = cum - probs < cfg.top_p
        cutoff = jnp.min(jnp.where(keep, sorted_l, jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


class CausalLMEngine:
    """Compiled prefill + decode for a causal LM exposing
    ``init_cache`` / ``forward_with_cache`` (LlamaForCausalLM, GPT...).

    Usage::

        eng = CausalLMEngine(model, max_batch=8, max_len=2048)
        out_ids = eng.generate(prompt_ids, GenerationConfig(max_new_tokens=64))
    """

    def __init__(self, model, max_batch: int, max_len: int):
        self.model = model
        self.max_batch = max_batch
        self.max_len = max_len
        self.params = {k: p.value for k, p in model.named_parameters()}

        def prefill(params, ids, caches):
            logits, caches = self._fwd(params, ids, caches, 0)
            return logits[:, -1], caches

        # one jitted prefill: jax.jit's own cache already specializes per
        # prompt-length/batch shape. decode stays keyed by GenerationConfig
        # because the config is *trace-static* (branching on do_sample/eos),
        # not shape-derived.
        self._prefill = jax.jit(prefill, donate_argnums=(2,))
        self._decode_cache = {}

    # -- pure functions -------------------------------------------------------
    def _fwd(self, params, ids, caches, pos):
        from ..core.autograd import no_grad

        with substituted_state(self.model, params), no_grad():
            logits, caches = self.model.forward_with_cache(
                Tensor(ids), caches, pos)
        return (logits.value if isinstance(logits, Tensor) else logits,
                caches)

    def _prefill_fn(self, prompt_len: int):
        return self._prefill

    def _decode_fn(self, n_steps: int, cfg: GenerationConfig):
        key_cfg = (n_steps, cfg.do_sample, cfg.temperature, cfg.top_k,
                   cfg.top_p, cfg.eos_token_id)
        if key_cfg not in self._decode_cache:
            def decode_n(params, first_tok, caches, pos0, key):
                # a row whose FIRST sampled token is already EOS must stay
                # frozen through the scan
                if cfg.eos_token_id is not None:
                    done_init = first_tok == cfg.eos_token_id
                else:
                    done_init = jnp.zeros(first_tok.shape, bool)

                def step(carry, _):
                    tok, caches, pos, key, done = carry
                    logits, caches = self._fwd(params, tok[:, None],
                                               caches, pos)
                    key, sub = jax.random.split(key)
                    nxt = _sample(logits[:, 0], sub, cfg)
                    if cfg.eos_token_id is not None:
                        nxt = jnp.where(done, cfg.eos_token_id, nxt)
                        done = done | (nxt == cfg.eos_token_id)
                    return (nxt, caches, pos + 1, key, done), nxt

                (_, caches, _, _, _), toks = jax.lax.scan(
                    step, (first_tok, caches, pos0, key, done_init), None,
                    length=n_steps)
                return jnp.swapaxes(toks, 0, 1), caches   # [B, n_steps]

            self._decode_cache[key_cfg] = jax.jit(
                decode_n, donate_argnums=(2,))
        return self._decode_cache[key_cfg]

    # -- public ---------------------------------------------------------------
    def generate(self, input_ids, config: Optional[GenerationConfig] = None):
        """input_ids: [B, prompt_len] (np/jnp/Tensor). Returns np.ndarray
        [B, prompt_len + max_new_tokens] (prompt + generated)."""
        cfg = config or GenerationConfig()
        ids = np.asarray(input_ids.value if isinstance(input_ids, Tensor)
                         else input_ids).astype(np.int32)
        b, plen = ids.shape
        if b > self.max_batch:
            raise ValueError(
                f"batch {b} exceeds max_batch={self.max_batch} the engine "
                f"was built for")
        if plen + cfg.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt({plen}) + max_new_tokens({cfg.max_new_tokens}) "
                f"exceeds engine max_len({self.max_len})")
        caches = self.model.init_cache(b, self.max_len)
        last_logits, caches = self._prefill_fn(plen)(self.params, ids, caches)
        key = jax.random.PRNGKey(cfg.seed)
        key, sub = jax.random.split(key)
        first = _sample(last_logits, sub, cfg)
        n_rest = cfg.max_new_tokens - 1
        if n_rest > 0:
            rest, caches = self._decode_fn(n_rest, cfg)(
                self.params, first, caches, jnp.int32(plen), key)
            gen = np.concatenate([np.asarray(first)[:, None],
                                  np.asarray(rest)], axis=1)
        else:
            gen = np.asarray(first)[:, None]
        return np.concatenate([ids, gen], axis=1)
