"""Prompt-lookup n-gram draft proposer (speculative decoding).

The draft source for LOSSLESS n-gram speculative decoding (prompt
lookup): continue the longest recent-suffix match found earlier in the
context. Extracted from ``generate_speculative`` so the OFFLINE path
(:meth:`CausalLMEngine.generate_speculative`) and the BATCHED serving
path (per-slot proposers inside the continuous-batching engines'
speculative decode segments) share one tested unit instead of two
copies of the suffix-match logic.

Two layers:

- :class:`NgramIndex` — the incremental n-gram -> continuation index
  over a token list the caller owns;
- :class:`NgramProposer` — per-SEQUENCE state (the context list + its
  index): seed it with the prompt, ``extend()`` it with each accepted
  token as decoding streams, ``propose()`` drafts. This is the object
  the serving engines keep per request id; a preempted/replayed request
  simply rebuilds it from ``prompt + generated`` (the index is a pure
  function of the context).

Plus the DEVICE twin: :func:`propose_device` runs the same suffix-match
lookup as a fixed-shape jax computation over per-slot history windows
held on device — the draft source of the continuous-batching engines'
``spec_mode="device"`` fused segment, where a host proposer would cost
a device→host sync per verify step.
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["NgramIndex", "NgramProposer", "propose_device"]


class NgramIndex:
    """Incremental prompt-lookup index: maps each n-gram (n <=
    ngram_max) to the continuation start of its most recent occurrence.
    Registration lags one position behind the context tail so the
    current suffix never matches itself; amortized O(ngram_max) per
    appended token (a fresh linear scan per proposal would be O(L) of
    host work per verify step — the latency this path exists to cut)."""

    def __init__(self, ngram_max: int):
        if not isinstance(ngram_max, (int, np.integer)) or ngram_max < 1:
            raise ValueError(
                f"ngram_max must be a positive int, got {ngram_max!r}")
        self.n_max = int(ngram_max)
        self.maps = {n: {} for n in range(1, self.n_max + 1)}
        self._reg = 0          # grams ending before this index are in

    def _register_upto(self, ctx, end):
        for j in range(self._reg, end):
            for n in range(1, min(self.n_max, j + 1) + 1):
                self.maps[n][tuple(ctx[j - n + 1:j + 1])] = j + 1
        self._reg = max(self._reg, end)

    def propose(self, ctx, k: int):
        """Up to ``k`` draft tokens continuing the longest recent
        suffix of ``ctx`` seen earlier in ``ctx`` (padded with the last
        draft — or the tail token on a total miss — to exactly k)."""
        L = len(ctx)
        self._register_upto(ctx, L - 1)   # exclude the current tail
        for n in range(min(self.n_max, L - 1), 0, -1):
            start = self.maps[n].get(tuple(ctx[L - n:]))
            if start is not None:
                cont = ctx[start:start + k]
                if cont:
                    return (cont + [cont[-1]] * (k - len(cont)))[:k]
        return [ctx[-1]] * k


class NgramProposer:
    """One sequence's draft proposer: context (prompt + every accepted
    token so far) plus its :class:`NgramIndex`, updated INCREMENTALLY
    as tokens stream — the serving engines call ``extend()`` with each
    segment step's accepted tokens and ``propose()`` once per verify
    forward, so per-step host work stays O(ngram_max * k), independent
    of the context length."""

    def __init__(self, tokens, draft_k: int, ngram_max: int = 3):
        if not isinstance(draft_k, (int, np.integer)) or draft_k < 1:
            raise ValueError(
                f"draft_k must be a positive int, got {draft_k!r}")
        self.k = int(draft_k)
        self.ctx: List[int] = [int(t) for t in np.asarray(tokens)
                               .reshape(-1)]
        self._index = NgramIndex(ngram_max)
        # host-side accounting the engines aggregate per segment
        self.proposed = 0
        self.accepted = 0

    def extend(self, tokens) -> None:
        """Append accepted tokens to the context (the index registers
        them lazily at the next ``propose``)."""
        self.ctx.extend(int(t) for t in tokens)

    def propose(self, k=None) -> List[int]:
        """Draft ``k`` (default: this proposer's ``draft_k``) tokens
        from the current context."""
        k = self.k if k is None else int(k)
        self.proposed += k
        return self._index.propose(self.ctx, k)


def propose_device(hist, hl, k: int, ngram_max: int):
    """Fixed-shape device twin of :meth:`NgramIndex.propose` over
    per-row history windows: ``hist`` is ``[B, H]`` int32 (each row the
    LAST ``hl[b] <= H`` context tokens, left-aligned), returns ``[B, k]``
    int32 drafts. For any row whose full context fits its window this
    produces EXACTLY the host proposer's drafts — longest suffix match
    first, most recent occurrence within a length, continuation padded
    with its own last token, total miss degrading to the tail token —
    so the host/device draft sources only diverge once a context
    outgrows the ring, and even then only in ACCEPTANCE (emitted tokens
    are always the model's own greedy picks; see the engines'
    speculative docs). Pure jnp (traceable inside ``lax.scan``); cost
    is O(H * ngram_max) per row per call, independent of context
    length."""
    H = hist.shape[1]
    n_max = int(ngram_max)
    k = int(k)

    def one(row, ln):
        j = jnp.arange(H)
        i = jnp.arange(n_max)
        # token at window position j-i (the gram ending at j, read
        # back-to-front) vs the current tail suffix token at ln-1-i;
        # distinct sentinels for the two out-of-range sides so a
        # padding position can never fake a match
        pos = j[:, None] - i[None, :]
        tokj = jnp.where(pos >= 0, row[jnp.clip(pos, 0, H - 1)], -1)
        tpos = ln - 1 - i
        tail = jnp.where(tpos >= 0, row[jnp.clip(tpos, 0, H - 1)], -2)
        run = jnp.cumprod((tokj == tail[None, :]).astype(jnp.int32),
                          axis=1)      # run[j, n-1]: n-gram match at j
        n_arr = i + 1
        # a valid length-n match needs the gram fully inside the window
        # (j >= n-1) and must exclude the current suffix itself
        # (j <= ln-2 — the host index registers one behind the tail)
        ok = ((run > 0) & (j[:, None] >= n_arr[None, :] - 1)
              & (j[:, None] <= ln - 2))
        # longest n wins, most recent j breaks ties — exactly the host
        # loop order (n descending, map holds the latest occurrence)
        score = jnp.where(ok, n_arr[None, :] * H + j[:, None], -1)
        j_sel = jnp.argmax(score) // n_max
        start = jnp.where(jnp.max(score) >= 0, j_sel + 1, ln - 1)
        # clamping to the window tail replicates the host's pad-with-
        # last (and the total-miss [tail]*k fallback, via start=ln-1)
        idx = jnp.clip(start + jnp.arange(k), 0, jnp.maximum(ln - 1, 0))
        return row[idx].astype(jnp.int32)

    return jax.vmap(one)(hist, hl)
