"""Prompt-lookup n-gram draft proposer (speculative decoding).

The draft source for LOSSLESS n-gram speculative decoding (prompt
lookup): continue the longest recent-suffix match found earlier in the
context. Extracted from ``generate_speculative`` so the OFFLINE path
(:meth:`CausalLMEngine.generate_speculative`) and the BATCHED serving
path (per-slot proposers inside the continuous-batching engines'
speculative decode segments) share one tested unit instead of two
copies of the suffix-match logic.

Two layers:

- :class:`NgramIndex` — the incremental n-gram -> continuation index
  over a token list the caller owns;
- :class:`NgramProposer` — per-SEQUENCE state (the context list + its
  index): seed it with the prompt, ``extend()`` it with each accepted
  token as decoding streams, ``propose()`` drafts. This is the object
  the serving engines keep per request id; a preempted/replayed request
  simply rebuilds it from ``prompt + generated`` (the index is a pure
  function of the context).
"""
from __future__ import annotations

from typing import List

import numpy as np

__all__ = ["NgramIndex", "NgramProposer"]


class NgramIndex:
    """Incremental prompt-lookup index: maps each n-gram (n <=
    ngram_max) to the continuation start of its most recent occurrence.
    Registration lags one position behind the context tail so the
    current suffix never matches itself; amortized O(ngram_max) per
    appended token (a fresh linear scan per proposal would be O(L) of
    host work per verify step — the latency this path exists to cut)."""

    def __init__(self, ngram_max: int):
        if not isinstance(ngram_max, (int, np.integer)) or ngram_max < 1:
            raise ValueError(
                f"ngram_max must be a positive int, got {ngram_max!r}")
        self.n_max = int(ngram_max)
        self.maps = {n: {} for n in range(1, self.n_max + 1)}
        self._reg = 0          # grams ending before this index are in

    def _register_upto(self, ctx, end):
        for j in range(self._reg, end):
            for n in range(1, min(self.n_max, j + 1) + 1):
                self.maps[n][tuple(ctx[j - n + 1:j + 1])] = j + 1
        self._reg = max(self._reg, end)

    def propose(self, ctx, k: int):
        """Up to ``k`` draft tokens continuing the longest recent
        suffix of ``ctx`` seen earlier in ``ctx`` (padded with the last
        draft — or the tail token on a total miss — to exactly k)."""
        L = len(ctx)
        self._register_upto(ctx, L - 1)   # exclude the current tail
        for n in range(min(self.n_max, L - 1), 0, -1):
            start = self.maps[n].get(tuple(ctx[L - n:]))
            if start is not None:
                cont = ctx[start:start + k]
                if cont:
                    return (cont + [cont[-1]] * (k - len(cont)))[:k]
        return [ctx[-1]] * k


class NgramProposer:
    """One sequence's draft proposer: context (prompt + every accepted
    token so far) plus its :class:`NgramIndex`, updated INCREMENTALLY
    as tokens stream — the serving engines call ``extend()`` with each
    segment step's accepted tokens and ``propose()`` once per verify
    forward, so per-step host work stays O(ngram_max * k), independent
    of the context length."""

    def __init__(self, tokens, draft_k: int, ngram_max: int = 3):
        if not isinstance(draft_k, (int, np.integer)) or draft_k < 1:
            raise ValueError(
                f"draft_k must be a positive int, got {draft_k!r}")
        self.k = int(draft_k)
        self.ctx: List[int] = [int(t) for t in np.asarray(tokens)
                               .reshape(-1)]
        self._index = NgramIndex(ngram_max)
        # host-side accounting the engines aggregate per segment
        self.proposed = 0
        self.accepted = 0

    def extend(self, tokens) -> None:
        """Append accepted tokens to the context (the index registers
        them lazily at the next ``propose``)."""
        self.ctx.extend(int(t) for t in tokens)

    def propose(self, k=None) -> List[int]:
        """Draft ``k`` (default: this proposer's ``draft_k``) tokens
        from the current context."""
        k = self.k if k is None else int(k)
        self.proposed += k
        return self._index.propose(self.ctx, k)
