"""PagedKVCache — page-pool KV cache manager for continuous batching.

Reference analog: fused_multi_transformer's per-batch cache slabs
(fused_multi_transformer_op.cu.h) sized ``[max_batch, max_len, ...]``.
Here the cache is a SHARED pool of fixed-size pages plus a per-slot page
table (ops/paged_attention.py consumes both), so:

- HBM holds the tokens in flight (rounded up to pages), not
  ``max_batch * max_len`` — with skewed lengths the pool can be a
  fraction of the dense slabs;
- any free page serves any slot: no fragmentation, admission between
  decode segments allocates pages for at most one segment of growth.

Split of responsibilities (mirrors the engine's host/device split):
page ALLOCATION is host-side Python between jitted segments (the free
list is plain state, like the engine's slot free list); page READS and
token WRITES are pure jittable functions of (pools, page_table) so they
ride inside compiled segment programs.
"""
from __future__ import annotations

import functools
import heapq
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["PageAllocator", "PagedKVCache", "write_tokens",
           "gather_dense"]


@functools.partial(jax.jit, donate_argnums=(0, 1))
def write_tokens(k_pool, v_pool, page_table, slots, positions, k_new,
                 v_new):
    """Scatter one new token per row into the pools (pure, jittable; the
    pools are DONATED — per-step writes must not copy the dominant HBM
    allocation, so callers follow the
    ``cache.k, cache.v = write_tokens(cache.k, cache.v, ...)`` pattern
    and never reuse the old arrays).

    slots: [N] int32 page-table rows; positions: [N] int32 token index
    within each sequence; k_new/v_new: [N, H, D]. Returns updated pools.
    Writes whose position has NO mapped page (table entry -1 — caller
    forgot ``ensure``) are DROPPED, never wrapped onto another
    sequence's page (JAX scatter would wrap the -1 to the last pool
    row otherwise).
    """
    ps = k_pool.shape[1]
    pages = page_table[slots, positions // ps]        # [N]
    # unmapped -> out-of-range sentinel; mode="drop" discards those rows
    pages = jnp.where(pages >= 0, pages, k_pool.shape[0])
    offs = positions % ps
    k_pool = k_pool.at[pages, offs].set(k_new.astype(k_pool.dtype),
                                        mode="drop")
    v_pool = v_pool.at[pages, offs].set(v_new.astype(v_pool.dtype),
                                        mode="drop")
    return k_pool, v_pool


@jax.jit
def gather_dense(pool, page_table, row):
    """Row's cache as a dense [max_pages*page_size, H, D] (testing/debug;
    the attention kernel never materializes this)."""
    return pool[jnp.maximum(page_table[row], 0)].reshape(
        -1, *pool.shape[2:])


class PageAllocator:
    """Page-table + free-list bookkeeping, pool-agnostic: ONE allocator
    (one table) serves every layer's pools — the table maps logical
    positions to page ids, and all layers use the same ids.

    ``num_pages * page_size`` bounds the TOTAL tokens in flight across
    all slots; ``max_pages`` bounds one sequence's length. Allocation
    (``ensure``) and free (``free_slot``) are host-side between
    segments; reads/writes are the pure functions above.
    """

    def __init__(self, num_pages: int, page_size: int, max_batch: int,
                 max_pages: int):
        self.page_size = page_size
        self.num_pages = num_pages
        # HOST-side numpy, mutated in place: ensure() runs for active
        # slots in the latency-critical gap between jitted segments, and
        # per-page jnp .at[].set updates would each be a device dispatch.
        # Consumers convert once per segment (jnp.asarray). -1 =
        # unmapped; the kernel clamps skipped entries to page 0.
        self.page_table = np.full((max_batch, max_pages), -1, np.int32)
        self._free: List[int] = list(range(num_pages))
        self._owned: Dict[int, List[int]] = {}
        # pool label so several allocators (multi-model serving) publish
        # side by side instead of clobbering one process-global gauge
        from .. import monitor

        self.monitor_pool = monitor.instance_label("pool")
        self._publish_occupancy()

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @staticmethod
    def _pages_gauge():
        from .. import monitor

        return monitor.gauge("paddle_tpu_kv_pages",
                             "KV-cache page pool occupancy by state",
                             ("pool", "state"))

    @staticmethod
    def _occupancy_gauge():
        from .. import monitor

        return monitor.gauge("paddle_tpu_kv_page_occupancy_ratio",
                             "fraction of the KV page pool in use",
                             ("pool",))

    def _publish_occupancy(self) -> None:
        """Push pool occupancy into the monitor (host-side mutations only
        happen in ensure/free_slot, so pushing there keeps the gauges
        exact with zero per-token cost)."""
        from .. import monitor

        if not monitor.enabled():
            return
        free = len(self._free)
        pages = self._pages_gauge()
        pages.labels(pool=self.monitor_pool, state="free").set(free)
        pages.labels(pool=self.monitor_pool,
                     state="used").set(self.num_pages - free)
        self._occupancy_gauge().labels(pool=self.monitor_pool).set(
            1.0 - free / self.num_pages if self.num_pages else 0.0)

    def pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def can_fit(self, slot: int, n_tokens: int) -> bool:
        have = len(self._owned.get(slot, []))
        return self.pages_for(n_tokens) - have <= len(self._free)

    def ensure(self, slot: int, n_tokens: int) -> None:
        """Grow ``slot``'s mapping to cover ``n_tokens`` positions.
        Raises RuntimeError when the pool is exhausted — the engine's
        admission control treats that like 'no free slot' and drains."""
        owned = self._owned.setdefault(slot, [])
        target = self.pages_for(n_tokens)
        if target > self.page_table.shape[1]:
            # an out-of-bounds table write would be silently dropped by
            # JAX while the page was still consumed — leak + wrong pages
            raise ValueError(
                f"slot {slot}: {n_tokens} tokens needs {target} pages > "
                f"max_pages={self.page_table.shape[1]} — grow max_pages "
                "(per-sequence length bound)")
        need = target - len(owned)
        if need <= 0:
            return
        if need > len(self._free):
            raise RuntimeError(
                f"page pool exhausted: slot {slot} needs {need} pages, "
                f"{len(self._free)} free — drain finished requests or "
                "grow num_pages")
        for _ in range(need):
            # heap pop (lowest page id first): ensure/free run in the
            # latency-critical inter-segment gap — a list pop(0) is O(n)
            # per page and the free() re-sort O(n log n) per retirement
            pid = heapq.heappop(self._free)
            self.page_table[slot, len(owned)] = pid
            owned.append(pid)
        self._publish_occupancy()

    def free_slot(self, slot: int) -> None:
        """Return the slot's pages to the pool (request retired)."""
        for pid in self._owned.pop(slot, []):
            heapq.heappush(self._free, pid)
        self.page_table[slot, :] = -1
        self._publish_occupancy()

    def close(self) -> None:
        """Retire this allocator's monitor series (idempotent). Without
        this, a dropped engine's pool gauges would export their last
        values forever and label cardinality would grow per engine."""
        try:
            pages = self._pages_gauge()
            pages.remove(pool=self.monitor_pool, state="free")
            pages.remove(pool=self.monitor_pool, state="used")
            self._occupancy_gauge().remove(pool=self.monitor_pool)
        except Exception:  # teardown-ordering safe
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class PagedKVCache(PageAllocator):
    """One layer's paged K/V pool + its allocator (single-layer
    convenience; multi-layer engines hold per-layer pools and ONE
    PageAllocator)."""

    def __init__(self, num_pages: int, page_size: int, num_heads: int,
                 head_dim: int, max_batch: int, max_pages: int,
                 dtype=jnp.bfloat16):
        super().__init__(num_pages, page_size, max_batch, max_pages)
        self.k = jnp.zeros((num_pages, page_size, num_heads, head_dim),
                           dtype)
        self.v = jnp.zeros_like(self.k)
