"""PagedKVCache — page-pool KV cache manager for continuous batching.

Reference analog: fused_multi_transformer's per-batch cache slabs
(fused_multi_transformer_op.cu.h) sized ``[max_batch, max_len, ...]``.
Here the cache is a SHARED pool of fixed-size pages plus a per-slot page
table (ops/paged_attention.py consumes both), so:

- HBM holds the tokens in flight (rounded up to pages), not
  ``max_batch * max_len`` — with skewed lengths the pool can be a
  fraction of the dense slabs;
- any free page serves any slot: no fragmentation, admission between
  decode segments allocates pages for at most one segment of growth.

Split of responsibilities (mirrors the engine's host/device split):
page ALLOCATION is host-side Python between jitted segments (the free
list is plain state, like the engine's slot free list); page READS and
token WRITES are pure jittable functions of (pools, page_table) so they
ride inside compiled segment programs.
"""
from __future__ import annotations

import functools
import heapq
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["PageAllocator", "PagedKVCache", "write_tokens",
           "gather_dense"]


@functools.partial(jax.jit, donate_argnums=(0, 1))
def write_tokens(k_pool, v_pool, page_table, slots, positions, k_new,
                 v_new):
    """Scatter one new token per row into the pools (pure, jittable; the
    pools are DONATED — per-step writes must not copy the dominant HBM
    allocation, so callers follow the
    ``cache.k, cache.v = write_tokens(cache.k, cache.v, ...)`` pattern
    and never reuse the old arrays).

    slots: [N] int32 page-table rows; positions: [N] int32 token index
    within each sequence; k_new/v_new: [N, H, D]. Returns updated pools.
    Writes whose position has NO mapped page (table entry -1 — caller
    forgot ``ensure``) are DROPPED, never wrapped onto another
    sequence's page (JAX scatter would wrap the -1 to the last pool
    row otherwise).
    """
    ps = k_pool.shape[1]
    pages = page_table[slots, positions // ps]        # [N]
    # unmapped -> out-of-range sentinel; mode="drop" discards those rows
    pages = jnp.where(pages >= 0, pages, k_pool.shape[0])
    offs = positions % ps
    k_pool = k_pool.at[pages, offs].set(k_new.astype(k_pool.dtype),
                                        mode="drop")
    v_pool = v_pool.at[pages, offs].set(v_new.astype(v_pool.dtype),
                                        mode="drop")
    return k_pool, v_pool


@jax.jit
def gather_dense(pool, page_table, row):
    """Row's cache as a dense [max_pages*page_size, H, D] (testing/debug;
    the attention kernel never materializes this)."""
    return pool[jnp.maximum(page_table[row], 0)].reshape(
        -1, *pool.shape[2:])


class PageAllocator:
    """Page-table + free-list bookkeeping, pool-agnostic: ONE allocator
    (one table) serves every layer's pools — the table maps logical
    positions to page ids, and all layers use the same ids.

    ``num_pages * page_size`` bounds the TOTAL tokens in flight across
    all slots; ``max_pages`` bounds one sequence's length. Allocation
    (``ensure``) and free (``free_slot``) are host-side between
    segments; reads/writes are the pure functions above.
    """

    def __init__(self, num_pages: int, page_size: int, max_batch: int,
                 max_pages: int, debug: bool = False):
        self.page_size = page_size
        self.num_pages = num_pages
        # debug=True runs the full check() invariant validator after
        # every mutating call (and the paged engine runs it once per
        # inter-segment gap): a reclaim bug fails LOUDLY at the faulty
        # op instead of silently scattering one request's KV into a
        # neighbour's pages. O(num_pages) per call — test/chaos tool,
        # not a production default.
        self.debug = bool(debug)
        self.preemptions = 0          # lifetime count, host-side
        # HOST-side numpy, mutated in place: ensure() runs for active
        # slots in the latency-critical gap between jitted segments, and
        # per-page jnp .at[].set updates would each be a device dispatch.
        # Consumers convert once per segment (jnp.asarray). -1 =
        # unmapped; the kernel clamps skipped entries to page 0.
        self.page_table = np.full((max_batch, max_pages), -1, np.int32)
        self._free: List[int] = list(range(num_pages))
        self._owned: Dict[int, List[int]] = {}
        # pool label so several allocators (multi-model serving) publish
        # side by side instead of clobbering one process-global gauge
        from .. import monitor

        self.monitor_pool = monitor.instance_label("pool")
        self._publish_occupancy()

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @staticmethod
    def _pages_gauge():
        from .. import monitor

        return monitor.gauge("paddle_tpu_kv_pages",
                             "KV-cache page pool occupancy by state",
                             ("pool", "state"))

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def occupancy(self) -> float:
        """Fraction of the pool in use right now (0.0 on an empty
        pool) — the number admission watermarks and the serving
        ``pressure`` surface read."""
        if not self.num_pages:
            return 0.0
        return 1.0 - len(self._free) / self.num_pages

    @staticmethod
    def _occupancy_gauge():
        from .. import monitor

        return monitor.gauge("paddle_tpu_kv_page_occupancy_ratio",
                             "fraction of the KV page pool in use",
                             ("pool",))

    def _publish_occupancy(self) -> None:
        """Push pool occupancy into the monitor (host-side mutations only
        happen in ensure/free_slot, so pushing there keeps the gauges
        exact with zero per-token cost)."""
        from .. import monitor

        if not monitor.enabled():
            return
        free = len(self._free)
        pages = self._pages_gauge()
        pages.labels(pool=self.monitor_pool, state="free").set(free)
        pages.labels(pool=self.monitor_pool,
                     state="used").set(self.num_pages - free)
        self._occupancy_gauge().labels(pool=self.monitor_pool).set(
            1.0 - free / self.num_pages if self.num_pages else 0.0)

    @staticmethod
    def _preempt_counter():
        from .. import monitor

        return monitor.counter(
            "paddle_tpu_kv_preemptions_total",
            "requests preempted to relieve KV page-pool memory "
            "pressure, by reason (pressure = growth needed the pages; "
            "unsatisfiable = could not fit even alone)",
            ("pool", "reason"))

    def count_preemption(self, reason: str = "pressure") -> None:
        """Record one preemption against this pool (the engine's
        ``preempt_request`` and the scheduler's admission-abort
        preemption path both land here, so ``preemptions`` is the
        pool-wide total whatever the victim's shape)."""
        self.preemptions += 1
        from .. import monitor

        if monitor.enabled():
            self._preempt_counter().labels(
                pool=self.monitor_pool, reason=reason).inc()

    def check(self) -> None:
        """Invariant validator: the free list and the per-slot owned
        pages must PARTITION ``range(num_pages)`` (no duplicates, no
        losses, no foreign ids), and every ``page_table`` row must
        mirror its slot's owned list exactly (owned prefix in order,
        ``-1`` tail). Raises RuntimeError on the first violation —
        called per-op under ``debug=True`` and once per gap by the
        paged engine, so a reclaim bug (double free, leaked page,
        stale table entry) fails loudly instead of corrupting a
        neighbour's KV."""
        owner = {}
        for pid in self._free:
            if pid in owner:
                raise RuntimeError(
                    f"page {pid} appears twice in the free list")
            owner[pid] = "free"
        for slot, pages in self._owned.items():
            for pid in pages:
                if pid in owner:
                    raise RuntimeError(
                        f"page {pid} owned by slot {slot} is also "
                        f"{owner[pid]}")
                owner[pid] = f"slot {slot}"
        if set(owner) != set(range(self.num_pages)):
            missing = sorted(set(range(self.num_pages)) - set(owner))
            foreign = sorted(set(owner) - set(range(self.num_pages)))
            raise RuntimeError(
                f"free ∪ owned does not partition the pool: "
                f"missing {missing}, foreign {foreign}")
        for slot in range(self.page_table.shape[0]):
            owned = self._owned.get(slot, [])
            row = self.page_table[slot]
            if (list(row[:len(owned)]) != list(owned)
                    or not (row[len(owned):] == -1).all()):
                raise RuntimeError(
                    f"page_table row {slot} inconsistent with owned "
                    f"pages {owned}: {row.tolist()}")

    def pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def can_fit(self, slot: int, n_tokens: int) -> bool:
        have = len(self._owned.get(slot, []))
        return self.pages_for(n_tokens) - have <= len(self._free)

    def ensure(self, slot: int, n_tokens: int) -> None:
        """Grow ``slot``'s mapping to cover ``n_tokens`` positions.
        Raises RuntimeError when the pool is exhausted — the engine's
        admission control treats that like 'no free slot' and drains."""
        owned = self._owned.setdefault(slot, [])
        target = self.pages_for(n_tokens)
        if target > self.page_table.shape[1]:
            # an out-of-bounds table write would be silently dropped by
            # JAX while the page was still consumed — leak + wrong pages
            raise ValueError(
                f"slot {slot}: {n_tokens} tokens needs {target} pages > "
                f"max_pages={self.page_table.shape[1]} — grow max_pages "
                "(per-sequence length bound)")
        need = target - len(owned)
        if need <= 0:
            return
        if need > len(self._free):
            raise RuntimeError(
                f"page pool exhausted: slot {slot} needs {need} pages, "
                f"{len(self._free)} free — drain finished requests or "
                "grow num_pages")
        for _ in range(need):
            # heap pop (lowest page id first): ensure/free run in the
            # latency-critical inter-segment gap — a list pop(0) is O(n)
            # per page and the free() re-sort O(n log n) per retirement
            pid = heapq.heappop(self._free)
            self.page_table[slot, len(owned)] = pid
            owned.append(pid)
        self._publish_occupancy()
        if self.debug:
            self.check()

    def free_slot(self, slot: int) -> None:
        """Return the slot's pages to the pool (request retired)."""
        for pid in self._owned.pop(slot, []):
            heapq.heappush(self._free, pid)
        self.page_table[slot, :] = -1
        self._publish_occupancy()
        if self.debug:
            self.check()

    def close(self) -> None:
        """Retire this allocator's monitor series (idempotent). Without
        this, a dropped engine's pool gauges would export their last
        values forever and label cardinality would grow per engine."""
        try:
            pages = self._pages_gauge()
            pages.remove(pool=self.monitor_pool, state="free")
            pages.remove(pool=self.monitor_pool, state="used")
            self._occupancy_gauge().remove(pool=self.monitor_pool)
        except Exception:  # teardown-ordering safe
            pass
        # the reason dimension is open-ended — retire by pool label
        try:
            from .. import monitor

            monitor.remove_series("paddle_tpu_kv_preemptions_total",
                                  pool=self.monitor_pool)
        except Exception:
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class PagedKVCache(PageAllocator):
    """One layer's paged K/V pool + its allocator (single-layer
    convenience; multi-layer engines hold per-layer pools and ONE
    PageAllocator)."""

    def __init__(self, num_pages: int, page_size: int, num_heads: int,
                 head_dim: int, max_batch: int, max_pages: int,
                 dtype=jnp.bfloat16):
        super().__init__(num_pages, page_size, max_batch, max_pages)
        self.k = jnp.zeros((num_pages, page_size, num_heads, head_dim),
                           dtype)
        self.v = jnp.zeros_like(self.k)
