"""PagedKVCache — page-pool KV cache manager for continuous batching.

Reference analog: fused_multi_transformer's per-batch cache slabs
(fused_multi_transformer_op.cu.h) sized ``[max_batch, max_len, ...]``.
Here the cache is a SHARED pool of fixed-size pages plus a per-slot page
table (ops/paged_attention.py consumes both), so:

- HBM holds the tokens in flight (rounded up to pages), not
  ``max_batch * max_len`` — with skewed lengths the pool can be a
  fraction of the dense slabs;
- any free page serves any slot: no fragmentation, admission between
  decode segments allocates pages for at most one segment of growth;
- with ``prefix_cache=True`` full pages of prompt KV become
  CONTENT-ADDRESSABLE and shareable (vLLM-style automatic prefix
  caching, Kwon et al. SOSP'23): every page carries a REFCOUNT, full
  prompt blocks are indexed by a chain hash (hash of the block's
  tokens + the previous block's hash, token-verified on match so a
  hash collision can never alias KV), a new request maps already
  resident blocks read-only instead of re-prefilling them, and the
  first write into a shared page goes through host-side COPY-ON-WRITE
  (:meth:`PageAllocator.cow`). Fully released cached pages PARK in an
  LRU free-but-indexed state — still a cache hit, but reclaimed on
  demand when the pool needs pages — so cache capacity is whatever the
  pool is not actively using.

Split of responsibilities (mirrors the engine's host/device split):
page ALLOCATION is host-side Python between jitted segments (the free
list is plain state, like the engine's slot free list); page READS and
token WRITES are pure jittable functions of (pools, page_table) so they
ride inside compiled segment programs.

TENSOR PARALLELISM (engine ``tp_degree=k``, see ``inference/tp.py``)
is invisible here BY CONSTRUCTION: pools shard on the kv-HEAD axis
(axis 2; int8 scales on axis 1), never on the page axis, so a page id
means "the same row of every shard's local pool slice" — the page
table replicates, and every function in this module (write/scatter/
copy/gather and all PageAllocator bookkeeping: refcounts, chain
hashes, CoW, LRU parking, ``check()``) runs UNMODIFIED under GSPMD
with head-sharded operands. Do not add per-shard branches to this
file; anything that would need one belongs in the attention ops'
shard_map wrap instead.
"""
from __future__ import annotations

import functools
import hashlib
import heapq
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["PageAllocator", "PagedKVCache", "write_tokens",
           "gather_dense", "scatter_rows", "copy_page", "gather_pages",
           "install_page", "write_tokens_q", "scatter_rows_q",
           "copy_page_q", "gather_pages_q", "gather_dense_q",
           "install_page_q"]

# chain-hash root: the "parent" of a prompt's first block
_ROOT = b"\x00" * 16


def _chain_root(salt: bytes) -> bytes:
    """Chain root for a (possibly salted) prefix namespace. The LoRA
    serving path salts with the adapter id (``name@generation``) so
    one adapter's cached blocks can never parent-match — and therefore
    never alias — another's (or the base model's)."""
    if not salt:
        return _ROOT
    return hashlib.blake2b(salt, digest_size=16).digest()


def _block_hash(parent: bytes, tokens: np.ndarray) -> bytes:
    """Chain hash of one page_size-token prompt block: a function of
    the block's tokens AND the whole prefix before it (via ``parent``),
    so equal blocks at different prefixes never alias. 128-bit blake2b
    — and matches are token-verified anyway, so a collision can
    degrade a hit, never corrupt KV."""
    return hashlib.blake2b(
        parent + np.ascontiguousarray(tokens, np.int32).tobytes(),
        digest_size=16).digest()


@functools.partial(jax.jit, donate_argnums=(0, 1))
def write_tokens(k_pool, v_pool, page_table, slots, positions, k_new,
                 v_new):
    """Scatter one new token per row into the pools (pure, jittable; the
    pools are DONATED — per-step writes must not copy the dominant HBM
    allocation, so callers follow the
    ``cache.k, cache.v = write_tokens(cache.k, cache.v, ...)`` pattern
    and never reuse the old arrays).

    slots: [N] int32 page-table rows; positions: [N] int32 token index
    within each sequence; k_new/v_new: [N, H, D]. Returns updated pools.
    Writes whose position has NO mapped page (table entry -1 — caller
    forgot ``ensure``) are DROPPED, never wrapped onto another
    sequence's page (JAX scatter would wrap the -1 to the last pool
    row otherwise). That drop is SILENT by design (one compiled
    program), which is why the paged engine's per-gap ``debug_pages``
    check also asserts no slot's live length extends past its mapped
    pages — a forgotten ensure() or copy-on-write surfaces there
    loudly instead of as wrong tokens far downstream
    (:meth:`PageAllocator.check_coverage`).
    """
    ps = k_pool.shape[1]
    pages = page_table[slots, positions // ps]        # [N]
    # unmapped -> out-of-range sentinel; mode="drop" discards those rows
    pages = jnp.where(pages >= 0, pages, k_pool.shape[0])
    offs = positions % ps
    k_pool = k_pool.at[pages, offs].set(k_new.astype(k_pool.dtype),
                                        mode="drop")
    v_pool = v_pool.at[pages, offs].set(v_new.astype(v_pool.dtype),
                                        mode="drop")
    return k_pool, v_pool


@functools.partial(jax.jit, donate_argnums=(0, 1),
                   static_argnames=("width",))
def scatter_rows(k_pool, v_pool, page_table, slot, start, limit,
                 mini_k, mini_v, *, width):
    """Masked variant of :func:`write_tokens` for ONE slot: scatter
    ``width`` consecutive mini-cache rows starting at TRACED position
    ``start``, dropping rows outside ``[start, limit)``. The
    prefix-cache install path uses this to write exactly the UNCACHED
    suffix of a warm prompt — positions below the cached coverage must
    never be re-written (their pages are shared read-only), and the
    fixed-width garbage tail past the prompt must never land in a
    shared page either. Programs are keyed on the STATIC ``width``
    (one per prefill bucket) and the pool/mini shapes — never on the
    offsets, so admissions with different cached coverage share one
    compiled program."""
    L = mini_k.shape[1]
    ps = k_pool.shape[1]
    # clamp the slice base so [base, base+width) stays inside the mini
    # (rows pulled in below `start` by the clamp are masked back out)
    base = jnp.clip(start, 0, L - width)
    pos = base + jnp.arange(width, dtype=jnp.int32)
    valid = (pos >= start) & (pos < limit)
    pages = page_table[slot, pos // ps]                      # [width]
    pages = jnp.where(valid & (pages >= 0), pages, k_pool.shape[0])
    offs = pos % ps
    k_new = jax.lax.dynamic_slice_in_dim(mini_k[0], base, width, axis=0)
    v_new = jax.lax.dynamic_slice_in_dim(mini_v[0], base, width, axis=0)
    k_pool = k_pool.at[pages, offs].set(k_new.astype(k_pool.dtype),
                                        mode="drop")
    v_pool = v_pool.at[pages, offs].set(v_new.astype(v_pool.dtype),
                                        mode="drop")
    return k_pool, v_pool


@functools.partial(jax.jit, donate_argnums=(0, 1))
def copy_page(k_pool, v_pool, src, dst):
    """Copy one page's rows src -> dst inside the pools (the device
    half of copy-on-write; src/dst are traced scalars, so every CoW in
    the process shares ONE compiled program per pool shape)."""
    k_pool = k_pool.at[dst].set(k_pool[src])
    v_pool = v_pool.at[dst].set(v_pool[src])
    return k_pool, v_pool


@functools.partial(jax.jit, donate_argnums=(0, 1))
def install_page(k_pool, v_pool, dst, k_rows, v_rows):
    """Write one page's worth of host rows into the pools at traced
    ``dst`` (the device half of a KV-page IMPORT: the wire carried the
    page's raw rows, this lands them — a pure copy in the pool dtype,
    the import-side mirror of :func:`copy_page`). ``dst`` is a traced
    scalar so every imported page in the process shares ONE compiled
    program per pool shape."""
    k_pool = k_pool.at[dst].set(k_rows.astype(k_pool.dtype))
    v_pool = v_pool.at[dst].set(v_rows.astype(v_pool.dtype))
    return k_pool, v_pool


@functools.partial(jax.jit, donate_argnums=(3, 4))
def gather_pages(k_pool, v_pool, pages, mini_k, mini_v):
    """Gather whole pages from the pools into the head of a dense mini
    cache (``mini[:, :len(pages)*page_size] = pool[pages]``): the warm
    admission path materializes the CACHED prefix KV this way — a pure
    copy, bitwise-identical to what the original prefill wrote — so the
    uncached tail can prefill against it at a traced offset. Callers
    pass a FIXED-width page vector (a full page-table row, ``-1``
    padded — clamped to page 0 here) so every warm admission shares
    ONE compiled program per pool shape; the junk rows gathered for
    unmapped entries sit past the cached coverage, where the tail
    prefill overwrites them or the causal/length mask hides them."""
    idx = jnp.maximum(pages, 0)
    uk = k_pool[idx].reshape(1, -1, *k_pool.shape[2:])
    uv = v_pool[idx].reshape(1, -1, *v_pool.shape[2:])
    mini_k = jax.lax.dynamic_update_slice_in_dim(
        mini_k, uk.astype(mini_k.dtype), 0, axis=1)
    mini_v = jax.lax.dynamic_update_slice_in_dim(
        mini_v, uv.astype(mini_v.dtype), 0, axis=1)
    return mini_k, mini_v


@jax.jit
def gather_dense(pool, page_table, row):
    """Row's cache as a dense [max_pages*page_size, H, D] (testing/debug;
    the attention kernel never materializes this)."""
    return pool[jnp.maximum(page_table[row], 0)].reshape(
        -1, *pool.shape[2:])


# -- int8 pools (kv_dtype="int8"): quantize-on-store twins ------------------
#
# Same shapes, same page-table convention, same drop-sentinel semantics
# as the functions above, but the pools are int8 and every page carries
# a per-(page, kv_head) f32 running-absmax scale that rides the page
# table exactly like the pages do: writes quantize on store and update
# the scales (quantization.kv.quant_store_rows — growth re-quantizes
# the page's existing rows, which is the bounded-not-bitwise part of
# the int8 contract), copies/gathers carry scales so CoW and warm
# prefix-cache admission stay pure page copies, and the paged
# attention read dequantizes INSIDE the kernel so the HBM read is
# int8 (ops/paged_attention.py).

@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def write_tokens_q(k_pool, v_pool, k_scale, v_scale, page_table, slots,
                   positions, k_new, v_new, limit=None):
    """Quantizing :func:`write_tokens`: one new token per row into int8
    pools, scales updated by running absmax. Unmapped positions drop —
    rows, absmax contributions and all (a dropped write must not
    inflate another page's scale).

    ``limit`` (traced scalar, optional): rows at ``positions >= limit``
    drop too. The unquantized install scatters its bucket-width pad
    tail as ignorable garbage; quantized, those rows would RATCHET the
    headroom pages' running absmax and cost real precision — and
    freshly claimed pages' floor-reset scales already dequantize their
    stale rows to ~0, so dropping the tail is strictly better."""
    from ..quantization.kv import quant_store_rows

    ps = k_pool.shape[1]
    pages = page_table[slots, positions // ps]
    ok = pages >= 0
    if limit is not None:
        ok = ok & (positions < limit)
    pages = jnp.where(ok, pages, k_pool.shape[0])
    offs = positions % ps
    k_pool, k_scale = quant_store_rows(k_pool, k_scale, pages, offs,
                                       k_new)
    v_pool, v_scale = quant_store_rows(v_pool, v_scale, pages, offs,
                                       v_new)
    return k_pool, v_pool, k_scale, v_scale


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3),
                   static_argnames=("width",))
def scatter_rows_q(k_pool, v_pool, k_scale, v_scale, page_table, slot,
                   start, limit, mini_k, mini_v, *, width):
    """Quantizing :func:`scatter_rows`: the masked one-slot install of
    a warm admission's uncached suffix. Masked-out rows (below the
    cached coverage or past the prompt) drop entirely, so shared
    read-only pages keep both their rows AND their scales untouched."""
    from ..quantization.kv import quant_store_rows

    L = mini_k.shape[1]
    ps = k_pool.shape[1]
    base = jnp.clip(start, 0, L - width)
    pos = base + jnp.arange(width, dtype=jnp.int32)
    valid = (pos >= start) & (pos < limit)
    pages = page_table[slot, pos // ps]
    pages = jnp.where(valid & (pages >= 0), pages, k_pool.shape[0])
    offs = pos % ps
    k_new = jax.lax.dynamic_slice_in_dim(mini_k[0], base, width, axis=0)
    v_new = jax.lax.dynamic_slice_in_dim(mini_v[0], base, width, axis=0)
    k_pool, k_scale = quant_store_rows(k_pool, k_scale, pages, offs,
                                       k_new)
    v_pool, v_scale = quant_store_rows(v_pool, v_scale, pages, offs,
                                       v_new)
    return k_pool, v_pool, k_scale, v_scale


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def copy_page_q(k_pool, v_pool, k_scale, v_scale, src, dst):
    """Quantizing :func:`copy_page`: copy-on-write must carry the
    page's SCALES with its rows — int8 rows are meaningless under
    another page's scale, so a CoW that copied only rows would corrupt
    the copy (the allocator's ``check()`` fails loudly on exactly that
    under ``debug_pages=True``)."""
    k_pool = k_pool.at[dst].set(k_pool[src])
    v_pool = v_pool.at[dst].set(v_pool[src])
    k_scale = k_scale.at[dst].set(k_scale[src])
    v_scale = v_scale.at[dst].set(v_scale[src])
    return k_pool, v_pool, k_scale, v_scale


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def install_page_q(k_pool, v_pool, k_scale, v_scale, dst, k_rows,
                   v_rows, k_s, v_s):
    """Quantizing :func:`install_page`: an imported int8 page carries
    its per-(page, kv_head) scale rows on the wire exactly like
    :func:`copy_page_q` carries them across a CoW — int8 rows are
    meaningless under another page's scale, so the import must never
    re-quantize (that would be a format conversion, not a page copy)."""
    k_pool = k_pool.at[dst].set(k_rows.astype(k_pool.dtype))
    v_pool = v_pool.at[dst].set(v_rows.astype(v_pool.dtype))
    k_scale = k_scale.at[dst].set(k_s.astype(k_scale.dtype))
    v_scale = v_scale.at[dst].set(v_s.astype(v_scale.dtype))
    return k_pool, v_pool, k_scale, v_scale


@functools.partial(jax.jit, donate_argnums=(5, 6))
def gather_pages_q(k_pool, v_pool, k_scale, v_scale, pages, mini_k,
                   mini_v):
    """Quantizing :func:`gather_pages`: dequantize whole resident pages
    into the head of a float mini cache (warm prefix admission — the
    tail prefill attends over the DEQUANTIZED prefix KV, which is what
    the fused-dequant decode reads see too, so warm and cold
    admissions agree to quantization error, not to a format skew)."""
    from ..quantization.kv import dequantize_page

    idx = jnp.maximum(pages, 0)
    uk = dequantize_page(k_pool[idx], k_scale[idx][:, None, :])
    uv = dequantize_page(v_pool[idx], v_scale[idx][:, None, :])
    uk = uk.reshape(1, -1, *k_pool.shape[2:])
    uv = uv.reshape(1, -1, *v_pool.shape[2:])
    mini_k = jax.lax.dynamic_update_slice_in_dim(
        mini_k, uk.astype(mini_k.dtype), 0, axis=1)
    mini_v = jax.lax.dynamic_update_slice_in_dim(
        mini_v, uv.astype(mini_v.dtype), 0, axis=1)
    return mini_k, mini_v


@jax.jit
def gather_dense_q(pool, scales, page_table, row):
    """Dequantized :func:`gather_dense` (testing/debug)."""
    from ..quantization.kv import dequantize_page

    idx = jnp.maximum(page_table[row], 0)
    return dequantize_page(pool[idx], scales[idx][:, None, :]).reshape(
        -1, *pool.shape[2:])


class PageAllocator:
    """Page-table + free-list bookkeeping, pool-agnostic: ONE allocator
    (one table) serves every layer's pools — the table maps logical
    positions to page ids, and all layers use the same ids.

    ``num_pages * page_size`` bounds the TOTAL tokens in flight across
    all slots; ``max_pages`` bounds one sequence's length. Allocation
    (``ensure``) and free (``free_slot``) are host-side between
    segments; reads/writes are the pure functions above.

    Every page carries a REFCOUNT (the number of slot-row appearances
    referencing it). Without ``prefix_cache`` every page's refcount is
    0 or 1 and the allocator behaves exactly like the pre-sharing one.
    With ``prefix_cache=True`` pages also move through a content index
    (see the module docstring): a page is in exactly ONE of three
    states — FREE (``_free`` heap), PARKED (refcount 0 but still
    indexed; an LRU of reclaimable cache hits), or REFERENCED
    (refcount >= 1, appearing in that many slot rows).
    """

    def __init__(self, num_pages: int, page_size: int, max_batch: int,
                 max_pages: int, debug: bool = False,
                 prefix_cache: bool = False, kv_dtype: str = "bf16"):
        from ..quantization.kv import KV_DTYPES

        if kv_dtype not in KV_DTYPES:
            raise ValueError(
                f"kv_dtype must be one of {KV_DTYPES}, got "
                f"{kv_dtype!r}")
        self.page_size = page_size
        self.num_pages = num_pages
        # int8 pools: host-side SCALE bookkeeping (the scale arrays
        # themselves live on device next to the pools). _scaled holds
        # the pages whose per-page scale rows are ESTABLISHED by
        # protocol — reset-fresh by the engine's claim flush, or copied
        # by a CoW — and check() enforces that every owned/parked page
        # is in it (a CoW that forgot to copy its scale fails loudly).
        # _fresh_scales queues newly claimed pages whose stale scale
        # rows the engine must reset to the floor before any write
        # (a previous owner's absmax must not ratchet a fresh page's
        # precision down); the engine drains it via take_fresh_scales.
        self.kv_dtype = kv_dtype
        self._scaled: set = set()
        self._fresh_scales: List[int] = []
        # HBM bytes the int8 pools avoided for pages claimed so far
        # (host-side total; the engine sets bytes_saved_per_page from
        # the real pool array sizes, scale overhead subtracted)
        self.bytes_saved_per_page = 0
        self.quant_bytes_saved = 0
        # debug=True runs the full check() invariant validator after
        # every mutating call (and the paged engine runs it once per
        # inter-segment gap): a reclaim bug fails LOUDLY at the faulty
        # op instead of silently scattering one request's KV into a
        # neighbour's pages. O(num_pages) per call — test/chaos tool,
        # not a production default.
        self.debug = bool(debug)
        self.prefix_cache = bool(prefix_cache)
        self.preemptions = 0          # lifetime count, host-side
        # HOST-side numpy, mutated in place: ensure() runs for active
        # slots in the latency-critical gap between jitted segments, and
        # per-page jnp .at[].set updates would each be a device dispatch.
        # Consumers convert once per segment (jnp.asarray). -1 =
        # unmapped; the kernel clamps skipped entries to page 0.
        # The mutable pool state below is OWNED by the engine-driving
        # (scheduler) thread — no lock by design: every mutation runs
        # between jitted segments, and the cross-thread readers
        # (Server.load()/healthz pressure) only take atomic int/len
        # snapshots. The guarded-by annotations document that ownership
        # for PT004 (documented, not lock-enforced — see MIGRATING.md).
        # guarded-by: scheduler-thread
        self.page_table = np.full((max_batch, max_pages), -1, np.int32)
        # guarded-by: scheduler-thread
        self._free: List[int] = list(range(num_pages))
        # guarded-by: scheduler-thread
        self._owned: Dict[int, List[int]] = {}
        self._ref: Dict[int, int] = {}         # pid -> refcount (>=1)
        self._shared = 0                       # pages with refcount > 1
        # prefix index (prefix_cache): chain hash <-> resident page
        self._index: Dict[bytes, int] = {}     # guarded-by: scheduler-thread
        self._hash_of: Dict[int, bytes] = {}   # pid -> hash
        self._tok_of: Dict[int, np.ndarray] = {}   # pid -> block tokens
        self._parent_of: Dict[int, bytes] = {}     # pid -> parent hash
        self._next: Dict[bytes, set] = {}      # parent hash -> {pid}
        # refcount-0 indexed pages, LRU order (oldest evicted first)
        # guarded-by: scheduler-thread
        self._parked: "OrderedDict[int, bytes]" = OrderedDict()
        # host-side prefix-cache accounting (monitor-independent)
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefix_tokens_saved = 0
        self.cow_copies = 0
        # pool label so several allocators (multi-model serving) publish
        # side by side instead of clobbering one process-global gauge
        from .. import monitor

        self.monitor_pool = monitor.instance_label("pool")
        self._publish_occupancy()

    # -- capacity accounting --------------------------------------------------
    @property
    def free_pages(self) -> int:
        """Strictly free pages (unindexed). Parked cache pages are NOT
        counted here — see :attr:`available_pages` for what an
        admission can actually claim."""
        return len(self._free)

    @property
    def cached_pages(self) -> int:
        """Refcount-0 pages parked in the prefix LRU: resident cache
        hits the pool reclaims on demand."""
        return len(self._parked)

    @property
    def available_pages(self) -> int:
        """Pages an allocation can claim right now: strictly free plus
        LRU-parked (a parked page is evicted from the index and reused
        the moment the pool needs it)."""
        return len(self._free) + len(self._parked)

    @property
    def shared_pages(self) -> int:
        """Pages referenced by MORE than one slot row right now — the
        sharing multiplier the prefix cache buys. Maintained
        incrementally on the 1<->2 refcount crossings (publish runs in
        the latency-critical gap; an O(pool) scan there would not);
        ``check()`` recomputes and cross-validates it."""
        return self._shared

    @staticmethod
    def _pages_gauge():
        from .. import monitor

        # kv_dtype label: at fixed HBM an int8 pool holds ~2x the
        # pages, so a pages number is only comparable WITH its storage
        # dtype attached
        return monitor.gauge("paddle_tpu_kv_pages",
                             "KV-cache page pool occupancy by state "
                             "and storage dtype",
                             ("pool", "state", "kv_dtype"))

    @property
    def used_pages(self) -> int:
        """Pages REFERENCED by at least one slot (parked cache pages
        are reclaimable, so they count as capacity, not use)."""
        return self.num_pages - len(self._free) - len(self._parked)

    @property
    def occupancy(self) -> float:
        """Fraction of the pool actually referenced right now (0.0 on
        an empty pool) — the number admission watermarks and the
        serving ``pressure`` surface read. LRU-parked cache pages are
        reclaimable and do not count."""
        if not self.num_pages:
            return 0.0
        return self.used_pages / self.num_pages

    @staticmethod
    def _occupancy_gauge():
        from .. import monitor

        return monitor.gauge("paddle_tpu_kv_page_occupancy_ratio",
                             "fraction of the KV page pool in use",
                             ("pool",))

    @staticmethod
    def _shared_gauge():
        from .. import monitor

        return monitor.gauge(
            "paddle_tpu_kv_shared_pages",
            "pages referenced by more than one slot (prefix-cache "
            "sharing)", ("pool",))

    def _publish_occupancy(self) -> None:
        """Push pool occupancy into the monitor (host-side mutations only
        happen in ensure/free_slot/map_shared/cow, so pushing there
        keeps the gauges exact with zero per-token cost)."""
        from .. import monitor

        if not monitor.enabled():
            return
        free = len(self._free)
        pages = self._pages_gauge()
        pages.labels(pool=self.monitor_pool, state="free",
                     kv_dtype=self.kv_dtype).set(free)
        pages.labels(pool=self.monitor_pool, state="used",
                     kv_dtype=self.kv_dtype).set(self.used_pages)
        if self.prefix_cache:
            pages.labels(pool=self.monitor_pool, state="cached",
                         kv_dtype=self.kv_dtype).set(len(self._parked))
            self._shared_gauge().labels(pool=self.monitor_pool).set(
                self.shared_pages)
        self._occupancy_gauge().labels(pool=self.monitor_pool).set(
            self.occupancy)

    @staticmethod
    def _preempt_counter():
        from .. import monitor

        return monitor.counter(
            "paddle_tpu_kv_preemptions_total",
            "requests preempted to relieve KV page-pool memory "
            "pressure, by reason (pressure = growth needed the pages; "
            "unsatisfiable = could not fit even alone)",
            ("pool", "reason"))

    @staticmethod
    def _prefix_hits_counter():
        from .. import monitor

        return monitor.counter(
            "paddle_tpu_kv_prefix_hits_total",
            "admissions that mapped at least one cached prompt-prefix "
            "page instead of re-prefilling it", ("pool",))

    @staticmethod
    def _prefix_saved_counter():
        from .. import monitor

        return monitor.counter(
            "paddle_tpu_kv_prefix_tokens_saved_total",
            "prompt tokens whose prefill compute was skipped because "
            "their KV was already resident (prefix-cache hits)",
            ("pool",))

    def count_preemption(self, reason: str = "pressure") -> None:
        """Record one preemption against this pool (the engine's
        ``preempt_request`` and the scheduler's admission-abort
        preemption path both land here, so ``preemptions`` is the
        pool-wide total whatever the victim's shape)."""
        self.preemptions += 1
        from .. import monitor

        if monitor.enabled():
            self._preempt_counter().labels(
                pool=self.monitor_pool, reason=reason).inc()

    @staticmethod
    def _quant_saved_counter():
        from .. import monitor

        return monitor.counter(
            "paddle_tpu_kv_quant_bytes_saved_total",
            "HBM bytes avoided by storing claimed KV pages int8 "
            "instead of the model cache dtype (per-page scale "
            "overhead already subtracted)", ("pool",))

    def _count_quant_claim(self) -> None:
        """One page claimed under int8 storage: account the HBM bytes
        the quantized layout avoided for it (host total + monitor
        counter; ``bytes_saved_per_page`` is 0 until the engine
        measures it from the real pools)."""
        if self.kv_dtype != "int8" or not self.bytes_saved_per_page:
            return
        self.quant_bytes_saved += self.bytes_saved_per_page
        from .. import monitor

        if monitor.enabled():
            self._quant_saved_counter().labels(
                pool=self.monitor_pool).inc(self.bytes_saved_per_page)

    def count_prefix_hit(self, tokens_saved: int) -> None:
        """Record one prefix-cache hit and the prompt tokens whose
        prefill compute it skipped (the engine calls this once per warm
        admission, AFTER the shared mapping succeeded)."""
        self.prefix_hits += 1
        self.prefix_tokens_saved += int(tokens_saved)
        from .. import tracing as _trace

        if _trace.enabled():
            _trace.event("prefix.hit", pool=self.monitor_pool,
                         tokens_saved=int(tokens_saved))
        from .. import monitor

        if monitor.enabled():
            self._prefix_hits_counter().labels(
                pool=self.monitor_pool).inc()
            if tokens_saved:
                self._prefix_saved_counter().labels(
                    pool=self.monitor_pool).inc(int(tokens_saved))

    # -- invariant validator --------------------------------------------------
    def check(self) -> None:
        """Invariant validator for the sharing era: every page must be
        in exactly ONE of free / parked / referenced, and the
        partition is by REFCOUNT ACCOUNTING — a page may appear in
        multiple slots' rows iff its refcount equals the appearance
        count; LRU-parked pages are indexed-but-reclaimable and appear
        in no row; every ``page_table`` row must mirror its slot's
        owned list exactly (owned prefix in order, ``-1`` tail); and
        the prefix index must be internally consistent. Raises
        RuntimeError on the first violation — called per-op under
        ``debug=True`` and once per gap by the paged engine, so a
        refcount leak, double free, or stale table entry fails loudly
        instead of corrupting a neighbour's KV."""
        owner = {}
        for pid in self._free:
            if pid in owner:
                raise RuntimeError(
                    f"page {pid} appears twice in the free list")
            owner[pid] = "free"
        for pid in self._parked:
            if pid in owner:
                raise RuntimeError(
                    f"page {pid} parked in the prefix LRU is also "
                    f"{owner[pid]}")
            if pid not in self._hash_of:
                raise RuntimeError(
                    f"page {pid} parked in the prefix LRU but not "
                    f"indexed")
            if self._ref.get(pid, 0):
                raise RuntimeError(
                    f"page {pid} parked with refcount "
                    f"{self._ref[pid]} (must be 0)")
            owner[pid] = "parked"
        appear: Dict[int, int] = {}
        for slot, pages in self._owned.items():
            for pid in pages:
                appear[pid] = appear.get(pid, 0) + 1
        for pid, n in appear.items():
            if pid in owner:
                raise RuntimeError(
                    f"page {pid} referenced by a slot is also "
                    f"{owner[pid]}")
            r = self._ref.get(pid, 0)
            if r != n:
                raise RuntimeError(
                    f"page {pid} appears in {n} slot row(s) but its "
                    f"refcount is {r} — sharing is legal only with a "
                    f"matching refcount (double-own / refcount leak)")
            owner[pid] = f"referenced(x{n})"
        for pid, r in self._ref.items():
            if appear.get(pid, 0) != r:
                raise RuntimeError(
                    f"page {pid} has refcount {r} but appears in "
                    f"{appear.get(pid, 0)} slot row(s) (refcount leak)")
        shared = sum(1 for r in self._ref.values() if r > 1)
        if shared != self._shared:
            raise RuntimeError(
                f"incremental shared-page counter {self._shared} "
                f"disagrees with the pool ({shared} pages with "
                f"refcount > 1)")
        if set(owner) != set(range(self.num_pages)):
            missing = sorted(set(range(self.num_pages)) - set(owner))
            foreign = sorted(set(owner) - set(range(self.num_pages)))
            raise RuntimeError(
                f"free ∪ parked ∪ referenced does not partition the "
                f"pool: missing {missing}, foreign {foreign}")
        for h, pid in self._index.items():
            if self._hash_of.get(pid) != h:
                raise RuntimeError(
                    f"prefix index maps {h.hex()} -> page {pid} but "
                    f"the page's hash is "
                    f"{self._hash_of.get(pid) and self._hash_of[pid].hex()}")
        for pid, h in self._hash_of.items():
            if self._index.get(h) != pid:
                raise RuntimeError(
                    f"page {pid} hashed but not (or differently) "
                    f"indexed")
            if pid not in self._tok_of or pid not in self._parent_of:
                raise RuntimeError(
                    f"indexed page {pid} missing token/parent records")
            if (self._ref.get(pid, 0) == 0
                    and pid not in self._parked):
                raise RuntimeError(
                    f"page {pid} indexed with refcount 0 but not "
                    f"parked (index leak)")
        for slot in range(self.page_table.shape[0]):
            owned = self._owned.get(slot, [])
            row = self.page_table[slot]
            if (list(row[:len(owned)]) != list(owned)
                    or not (row[len(owned):] == -1).all()):
                raise RuntimeError(
                    f"page_table row {slot} inconsistent with owned "
                    f"pages {owned}: {row.tolist()}")
        if self.kv_dtype == "int8":
            # scale accounting (int8 pools): every page whose KV is
            # readable — referenced by a slot or parked in the prefix
            # LRU — must have ESTABLISHED scale rows (reset-fresh at
            # claim, or copied by CoW); a page on the free heap must
            # not (freed pages reset their scale bookkeeping). The
            # canonical failure this catches: a copy-on-write that
            # copied the page's rows but forgot its scales.
            for pid, state in owner.items():
                if state == "free":
                    if pid in self._scaled:
                        raise RuntimeError(
                            f"free page {pid} still marked "
                            f"scale-established (freed pages must "
                            f"reset scale bookkeeping)")
                elif pid not in self._scaled:
                    raise RuntimeError(
                        f"{state} page {pid} has no established "
                        f"scales — a copy-on-write or install forgot "
                        f"to carry the per-page scale rows")
            for pid in self._fresh_scales:
                if owner.get(pid) == "free" or pid >= self.num_pages:
                    raise RuntimeError(
                        f"fresh-scale queue holds page {pid} which is "
                        f"{owner.get(pid, 'foreign')} — reset queue "
                        f"out of sync with claims")

    def check_coverage(self, slot: int, live_len: int,
                       write_ahead: int = 1) -> None:
        """Per-gap hardening against :func:`write_tokens`' silent drop
        (and a forgotten copy-on-write): ``slot``'s live length must
        not extend past its mapped pages, and the page the next decode
        write lands in must be PRIVATE (refcount 1, unindexed) —
        otherwise the write would either be dropped silently or mutate
        a shared/indexed page other requests read. The paged engine
        calls this for every live slot per gap under ``debug_pages``."""
        owned = self._owned.get(slot, [])
        if self.pages_for(live_len) > len(owned):
            raise RuntimeError(
                f"slot {slot}: live length {live_len} extends past its "
                f"{len(owned)} mapped page(s) — a KV write was (or "
                f"would be) silently dropped (forgot ensure()/CoW?)")
        max_len = self.page_size * self.page_table.shape[1]
        for pos in range(live_len, min(live_len + write_ahead, max_len)):
            # unmapped growth is the optimistic-mode grow/exhaustion
            # path's job, not a CoW bug — needs_cow returns False there
            if self.needs_cow(slot, pos):
                raise RuntimeError(
                    f"slot {slot}: next decode write at position {pos} "
                    f"lands in shared/indexed page "
                    f"{owned[pos // self.page_size]} — missing "
                    f"copy-on-write")
            if (self.kv_dtype == "int8"
                    and pos // self.page_size < len(owned)
                    and owned[pos // self.page_size] not in self._scaled):
                raise RuntimeError(
                    f"slot {slot}: imminent int8 write at position "
                    f"{pos} lands in page "
                    f"{owned[pos // self.page_size]} whose scales were "
                    f"never established (missing CoW scale copy or "
                    f"claim reset)")

    def check_scales(self, k_scale, v_scale) -> None:
        """Device-side half of the int8 scale invariants (the paged
        engine pulls one layer's scale arrays per gap under
        ``debug_pages=True``): every owned/parked/shared page's scales
        must be FINITE and positive — NaN/inf here means a quantized
        store was fed garbage and every future dequant of the page is
        poisoned."""
        ks = np.asarray(k_scale)
        vs = np.asarray(v_scale)
        live = sorted(set().union(
            *(set(p) for p in self._owned.values())) | set(self._parked))
        for pid in live:
            for name, arr in (("k", ks), ("v", vs)):
                row = arr[pid]
                if not np.all(np.isfinite(row)) or np.any(row <= 0):
                    raise RuntimeError(
                        f"page {pid}: non-finite/non-positive {name} "
                        f"scale row {row.tolist()} — quantized store "
                        f"fed garbage, dequant poisoned")

    def needs_cow(self, slot: int, pos: int) -> bool:
        """True when the page mapped at token position ``pos`` of
        ``slot`` is shared (refcount > 1) or indexed — a write there
        must go through :meth:`cow` first. False for private pages and
        unmapped positions (growth is ``ensure``'s job, not CoW's)."""
        owned = self._owned.get(slot, [])
        idx = pos // self.page_size
        if idx >= len(owned):
            return False
        pid = owned[idx]
        return self._ref.get(pid, 0) > 1 or pid in self._hash_of

    def pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def covered_tokens(self, slot: int) -> int:
        """Token positions ``slot``'s mapped pages cover (writes past
        this are silently dropped by :func:`write_tokens` — the
        speculative verify step caps per-row acceptance here)."""
        return len(self._owned.get(slot, [])) * self.page_size

    def can_fit(self, slot: int, n_tokens: int) -> bool:
        have = len(self._owned.get(slot, []))
        return (self.pages_for(n_tokens) - have
                <= len(self._free) + len(self._parked))

    def _claim_page(self) -> int:
        """One fresh private page: from the free heap, else by evicting
        the LRU-oldest parked cache page (its index entries drop — a
        future lookup simply misses)."""
        if self._free:
            # heap pop (lowest page id first): ensure/free run in the
            # latency-critical inter-segment gap — a list pop(0) is O(n)
            # per page and the free() re-sort O(n log n) per retirement
            return self._note_claim(heapq.heappop(self._free))
        if self._parked:
            pid, _h = self._parked.popitem(last=False)
            self._unindex(pid)
            from .. import tracing as _trace

            if _trace.enabled():
                # LRU eviction of a parked cache page: future lookups
                # for its block will MISS — the event that explains a
                # hit-rate drop under pool pressure
                _trace.event("prefix.evict", pool=self.monitor_pool,
                             page=pid)
            return self._note_claim(pid)
        raise RuntimeError("page pool exhausted")

    def _note_claim(self, pid: int) -> int:
        """Scale bookkeeping for a freshly claimed page (int8): its
        device scale rows are a previous owner's leftovers, so it is
        UN-established (``_scaled`` drop) and queued for the engine's
        reset flush. ``ensure`` re-establishes it (the claim flush
        covers it); ``cow`` instead pulls it off the fresh queue and
        waits for :meth:`note_scale_copied`."""
        if self.kv_dtype == "int8":
            self._scaled.discard(pid)
            self._fresh_scales.append(pid)
            self._count_quant_claim()
        return pid

    def note_scale_copied(self, pid: int) -> None:
        """The engine copied scale rows onto ``pid`` on device
        (copy-on-write's second half): mark its scales established.
        Under ``debug=True`` this is also where the post-CoW invariant
        check runs — :meth:`cow` cannot check itself because its own
        return value IS the copy instruction."""
        if self.kv_dtype != "int8":
            return
        self._scaled.add(pid)
        if self.debug:
            self.check()

    def take_fresh_scales(self) -> List[int]:
        """Drain the queue of claimed-but-unreset pages (int8). The
        engine calls this at its write choke points and resets the
        listed pages' scale rows to the floor IN ONE fixed-shape masked
        program before any quantized write — never per page, never a
        shape-keyed recompile."""
        out, self._fresh_scales = self._fresh_scales, []
        return out

    def _unindex(self, pid: int) -> None:
        h = self._hash_of.pop(pid, None)
        if h is not None and self._index.get(h) == pid:
            del self._index[h]
        self._tok_of.pop(pid, None)
        parent = self._parent_of.pop(pid, None)
        if parent is not None:
            kids = self._next.get(parent)
            if kids is not None:
                kids.discard(pid)
                if not kids:
                    del self._next[parent]

    def _release_ref(self, pid: int) -> None:
        """Drop one reference; at zero the page parks (still indexed)
        or returns to the free heap."""
        n = self._ref.get(pid, 0) - 1
        if n == 1:
            self._shared -= 1
        if n > 0:
            self._ref[pid] = n
            return
        self._ref.pop(pid, None)
        if pid in self._hash_of:
            self._parked[pid] = self._hash_of[pid]
            self._parked.move_to_end(pid)
            from .. import tracing as _trace

            if _trace.enabled():
                _trace.event("prefix.park", pool=self.monitor_pool,
                             page=pid)
        else:
            # freed pages reset their scale bookkeeping: whatever
            # scale rows they carry belong to a dead owner (parked
            # pages keep theirs — their KV stays readable). A claim
            # freed before the engine's reset flush ran (aborted
            # admission) also leaves the fresh queue — it re-queues on
            # its next claim.
            self._scaled.discard(pid)
            if pid in self._fresh_scales:
                self._fresh_scales.remove(pid)
            heapq.heappush(self._free, pid)

    def ensure(self, slot: int, n_tokens: int) -> None:
        """Grow ``slot``'s mapping to cover ``n_tokens`` positions with
        PRIVATE pages (already-mapped pages — shared prefix ones
        included — count toward coverage). Raises RuntimeError when the
        pool is exhausted — the engine's admission control treats that
        like 'no free slot' and drains."""
        owned = self._owned.setdefault(slot, [])
        target = self.pages_for(n_tokens)
        if target > self.page_table.shape[1]:
            # an out-of-bounds table write would be silently dropped by
            # JAX while the page was still consumed — leak + wrong pages
            raise ValueError(
                f"slot {slot}: {n_tokens} tokens needs {target} pages > "
                f"max_pages={self.page_table.shape[1]} — grow max_pages "
                "(per-sequence length bound)")
        need = target - len(owned)
        if need <= 0:
            return
        if need > len(self._free) + len(self._parked):
            raise RuntimeError(
                f"page pool exhausted: slot {slot} needs {need} pages, "
                f"{len(self._free) + len(self._parked)} reclaimable — "
                "drain finished requests or grow num_pages")
        for _ in range(need):
            pid = self._claim_page()
            self._ref[pid] = 1
            if self.kv_dtype == "int8":
                # established by protocol: the claim sits on the fresh
                # queue and the engine's flush resets its scale rows
                # before any write lands in it
                self._scaled.add(pid)
            self.page_table[slot, len(owned)] = pid
            owned.append(pid)
        self._publish_occupancy()
        if self.debug:
            self.check()

    def free_slot(self, slot: int) -> None:
        """Release the slot's references (request retired): private
        pages return to the pool; shared pages survive for their other
        referents; indexed pages with no referent left park in the
        prefix LRU (still a cache hit, reclaimable on demand)."""
        for pid in self._owned.pop(slot, []):
            self._release_ref(pid)
        self.page_table[slot, :] = -1
        self._publish_occupancy()
        if self.debug:
            self.check()

    # -- prefix cache (content-addressable shared pages) ----------------------
    def lookup_prefix(self, tokens,
                      salt: bytes = b"") -> Tuple[List[int], int,
                                                  List[bytes]]:
        """Longest resident cached prefix of ``tokens`` (1-D int ids).

        Walks the full-block chain hash (token-verified per block),
        then tries ONE partial block: an indexed child of the last
        matched chain point whose leading tokens extend the match
        (divergent-suffix / mid-tail sharing — the page the caller must
        copy-on-write before its first write). Returns
        ``(pids, coverage, hashes)``: the resident pages to map
        read-only in order, the token coverage they provide
        (``<= len(tokens)``), and the full-block chain hashes (for
        registering the blocks the caller will prefill). Touches the
        LRU order of parked hits; claims no references —
        :meth:`map_shared` does.

        ``salt`` namespaces the whole chain: a non-empty salt replaces
        the chain ROOT, so hashes under different salts can never match
        each other's blocks. The LoRA serving path salts with the
        adapter's ``name@generation`` — cached KV is a function of the
        WEIGHTS that produced it, so a base-model block must never
        warm-hit an adapter's admission (or vice versa), and a reload
        of the same adapter name gets a fresh namespace. ``b""`` (the
        default) keeps the pre-LoRA root: base-model traffic on a
        LoRA-enabled engine shares KV with pre-LoRA admissions."""
        self.prefix_lookups += 1
        toks = np.ascontiguousarray(
            np.asarray(tokens).reshape(-1), np.int32)
        ps = self.page_size
        nfull = len(toks) // ps
        root = _chain_root(salt)
        hashes: List[bytes] = []
        h = root
        for b in range(nfull):
            h = _block_hash(h, toks[b * ps:(b + 1) * ps])
            hashes.append(h)
        pids: List[int] = []
        matched = 0
        while matched < nfull:
            pid = self._index.get(hashes[matched])
            if pid is None or not np.array_equal(
                    self._tok_of[pid], toks[matched * ps:
                                            (matched + 1) * ps]):
                break
            pids.append(pid)
            matched += 1
        cov = matched * ps
        rem = toks[cov:]
        if len(rem):
            parent = hashes[matched - 1] if matched else root
            best, best_m = None, 0
            for pid in self._next.get(parent, ()):
                bt = self._tok_of.get(pid)
                if bt is None:
                    continue
                lim = min(len(rem), ps)
                m = 0
                while m < lim and int(bt[m]) == int(rem[m]):
                    m += 1
                if m > best_m:
                    best, best_m = pid, m
            if best is not None and best_m > 0:
                pids.append(best)
                cov += best_m
        for pid in pids:
            if pid in self._parked:
                self._parked.move_to_end(pid)
        return pids, cov, hashes

    def map_shared(self, slot: int, pids: List[int]) -> None:
        """Map resident cached pages read-only into an EMPTY slot's
        table (refcount++ each; parked pages leave the LRU but stay
        indexed). Prefill and page claiming skip the coverage these
        provide; the first write into any of them must go through
        :meth:`cow`."""
        if self._owned.get(slot):
            raise RuntimeError(
                f"map_shared needs an empty slot, slot {slot} already "
                f"owns {len(self._owned[slot])} page(s)")
        if not pids:
            return
        owned = self._owned.setdefault(slot, [])
        for pid in pids:
            self._parked.pop(pid, None)
            n = self._ref.get(pid, 0) + 1
            if n == 2:
                self._shared += 1
            self._ref[pid] = n
            self.page_table[slot, len(owned)] = pid
            owned.append(pid)
        self._publish_occupancy()
        if self.debug:
            self.check()

    def cow(self, slot: int, page_idx: int) -> Tuple[int, int]:
        """Copy-on-write bookkeeping for ``slot``'s page at
        ``page_idx``: claim a fresh private page, swap the table entry,
        release the old reference (the shared original survives for its
        other referents / stays parked-indexed). Returns
        ``(old_pid, new_pid)`` — the caller owns the device-side row
        copy (:func:`copy_page`) BEFORE any write to the new page."""
        owned = self._owned[slot]
        old = owned[page_idx]
        new = self._claim_page()
        if self.kv_dtype == "int8":
            # NOT a fresh-reset page: the caller's device copy brings
            # the SOURCE page's scales over (copy_page_q), and
            # note_scale_copied marks it established. Until then the
            # page is deliberately un-established so a forgotten scale
            # copy fails the next check() loudly.
            self._fresh_scales.remove(new)
        self._ref[new] = 1
        owned[page_idx] = new
        self.page_table[slot, page_idx] = new
        self._release_ref(old)
        self.cow_copies += 1
        from .. import tracing as _trace

        if _trace.enabled():
            _trace.event("prefix.cow", pool=self.monitor_pool,
                         slot=slot, old=old, new=new)
        self._publish_occupancy()
        if self.debug and self.kv_dtype != "int8":
            # int8 defers to note_scale_copied: between this return and
            # the device copy the new page is legitimately in the
            # not-yet-scaled state check() exists to reject
            self.check()
        return old, new

    def register_blocks(self, slot: int, hashes: List[bytes], tokens,
                        start_block: int, end_block: int,
                        salt: bytes = b"") -> None:
        """Index ``slot``'s fully-written prompt blocks
        ``[start_block, end_block)`` under their chain hashes so future
        admissions can map them read-only. Only PRIVATE pages
        (refcount 1, unindexed) register; an already-taken hash keeps
        its first page (first writer wins — both hold identical KV).
        ``salt`` must match the ``lookup_prefix`` call that produced
        ``hashes`` — it only affects block 0's recorded parent (the
        salted chain root), which is what keeps partial-block child
        lookups inside one adapter's namespace."""
        if not self.prefix_cache:
            return
        owned = self._owned.get(slot, [])
        toks = np.ascontiguousarray(
            np.asarray(tokens).reshape(-1), np.int32)
        ps = self.page_size
        for b in range(start_block, end_block):
            if b >= len(owned) or b >= len(hashes):
                break
            pid = owned[b]
            h = hashes[b]
            if (h in self._index or pid in self._hash_of
                    or self._ref.get(pid, 0) != 1):
                continue
            self._index[h] = pid
            self._hash_of[pid] = h
            self._tok_of[pid] = toks[b * ps:(b + 1) * ps].copy()
            parent = hashes[b - 1] if b else _chain_root(salt)
            self._parent_of[pid] = parent
            self._next.setdefault(parent, set()).add(pid)
        if self.debug:
            self.check()

    def adopt_block(self, h: bytes, parent: bytes,
                    tokens) -> Optional[int]:
        """Adopt one IMPORTED full block into the prefix index as a
        PARKED page (refcount 0, LRU-reclaimable): the bookkeeping half
        of a cross-process KV-page import. The caller owns the device
        copy (:func:`install_page` / :func:`install_page_q` onto the
        returned pid, then — int8 — :meth:`note_scale_copied`, same
        deferred-check contract as CoW).

        Idempotent by content address: a hash already resident (token-
        verified or not — first writer wins, both hold identical KV)
        returns ``None`` and claims nothing, which is what makes a
        replayed/duplicated handoff a dedup no-op fleet-wide. ``parent``
        is the previous block's chain hash (or the salted chain root
        for block 0) — recording it keeps imported blocks reachable by
        the partial-block child walk exactly like locally written ones.
        Raises RuntimeError when the pool has no reclaimable page."""
        if not self.prefix_cache:
            raise RuntimeError(
                "adopt_block needs the prefix cache (an unindexed "
                "import could never be found again — enable "
                "cache_prefixes on the importing engine)")
        toks = np.ascontiguousarray(
            np.asarray(tokens).reshape(-1), np.int32)
        if len(toks) != self.page_size:
            raise ValueError(
                f"adopt_block takes exactly one FULL block "
                f"({self.page_size} tokens), got {len(toks)}")
        if h in self._index:
            return None
        pid = self._claim_page()
        if self.kv_dtype == "int8":
            # not a fresh-reset page: the wire carried the source
            # page's scale rows and install_page_q lands them; until
            # note_scale_copied the page is deliberately un-established
            # so a forgotten scale install fails check() loudly
            self._fresh_scales.remove(pid)
        self._index[h] = pid
        self._hash_of[pid] = h
        self._tok_of[pid] = toks.copy()
        self._parent_of[pid] = parent
        self._next.setdefault(parent, set()).add(pid)
        self._parked[pid] = h
        self._parked.move_to_end(pid)
        self._publish_occupancy()
        if self.debug and self.kv_dtype != "int8":
            self.check()
        return pid

    def clear_prefix_index(self) -> None:
        """Drop the whole content index and return parked pages to the
        free heap (engine ``reset_state``: the pools are rebuilt from
        zeros, so every cached block's KV is gone)."""
        for pid in list(self._parked):
            self._scaled.discard(pid)
            heapq.heappush(self._free, pid)
        self._parked.clear()
        self._index.clear()
        self._hash_of.clear()
        self._tok_of.clear()
        self._parent_of.clear()
        self._next.clear()
        self._publish_occupancy()
        if self.debug:
            self.check()

    def set_kv_dtype(self, kv_dtype: str) -> None:
        """Swap this pool's storage-dtype bookkeeping (the ENGINE owns
        rebuilding the device pools — only call through its idle-only
        ``set_kv_dtype``). Retires the old ``kv_dtype``-labeled gauge
        points so the pages gauge never exports two dtypes for one
        pool, and resets the scale bookkeeping (fresh pools start with
        floor scales, nothing established or pending)."""
        from ..quantization.kv import KV_DTYPES

        if kv_dtype not in KV_DTYPES:
            raise ValueError(
                f"kv_dtype must be one of {KV_DTYPES}, got "
                f"{kv_dtype!r}")
        if kv_dtype == self.kv_dtype:
            return
        self._retire_pages_gauge()
        self.kv_dtype = kv_dtype
        self._scaled.clear()
        self._fresh_scales.clear()
        self._publish_occupancy()

    def _retire_pages_gauge(self) -> None:
        try:
            from .. import monitor

            monitor.remove_series("paddle_tpu_kv_pages",
                                  pool=self.monitor_pool)
        except Exception:  # teardown-ordering safe
            pass

    def close(self) -> None:
        """Retire this allocator's monitor series (idempotent). Without
        this, a dropped engine's pool gauges would export their last
        values forever and label cardinality would grow per engine."""
        self._retire_pages_gauge()
        try:
            self._occupancy_gauge().remove(pool=self.monitor_pool)
        except Exception:  # teardown-ordering safe
            pass
        # open-ended label dimensions — retire by pool label
        try:
            from .. import monitor

            for name in ("paddle_tpu_kv_preemptions_total",
                         "paddle_tpu_kv_prefix_hits_total",
                         "paddle_tpu_kv_prefix_tokens_saved_total",
                         "paddle_tpu_kv_shared_pages",
                         "paddle_tpu_kv_quant_bytes_saved_total"):
                monitor.remove_series(name, pool=self.monitor_pool)
        except Exception:
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class PagedKVCache(PageAllocator):
    """One layer's paged K/V pool + its allocator (single-layer
    convenience; multi-layer engines hold per-layer pools and ONE
    PageAllocator)."""

    def __init__(self, num_pages: int, page_size: int, num_heads: int,
                 head_dim: int, max_batch: int, max_pages: int,
                 dtype=jnp.bfloat16):
        super().__init__(num_pages, page_size, max_batch, max_pages)
        self.k = jnp.zeros((num_pages, page_size, num_heads, head_dim),
                           dtype)
        self.v = jnp.zeros_like(self.k)
