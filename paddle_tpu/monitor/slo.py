"""SLO-aware serving observability: mergeable latency digests,
per-tenant goodput, and burn-rate windows.

The serving fleet (PRs 2-14) exports raw counters and per-replica
latency histograms; this module adds the layer an operator actually
pages on:

- :class:`LatencyDigest` — a streaming latency digest over FIXED
  log-spaced buckets. Because the bucket boundaries are a pure function
  of the (lo, hi, buckets_per_decade) config — never of the data —
  merging two digests is an elementwise counter add, and a percentile
  read off the merged digest is EXACTLY the percentile of the
  concatenated streams at digest resolution (one bucket width,
  ``10**(1/buckets_per_decade)`` relative). This is the invariant the
  fleet ``GET /stats`` rollup rides: fleet p99 is computed by MERGING
  replica digests, never by averaging replica percentiles (averaging
  percentiles is statistically meaningless — the classic monitoring
  bug this module exists to make structurally impossible).
- :class:`RollingDigest` — the same digest over a sliding time window
  (sharded by epoch; old shards expire wholesale), for rates that must
  reflect NOW: the slow-replica skew detector reads each replica's
  rolling TPOT p50 from one of these.
- :class:`SLOPolicy` — per-request latency thresholds
  (``ttft_p99_s`` / ``tpot_p99_s`` / ``e2e_p99_s``) plus a goodput
  target. A request MEETS the SLO when every configured threshold
  holds; **goodput** is the fraction of service-terminal requests
  (finished + failed; cancelled/expired are client verdicts and don't
  count) that met it — the distserve/splitwise quantity serving
  actually optimizes, as opposed to raw throughput. **Burn rate** is
  the SRE-shaped ``miss_fraction / (1 - goodput_target)`` over a fast
  and a slow window: burn > 1 means the error budget is being spent
  faster than it accrues.
- :class:`SLOTracker` — the per-server aggregation point: one digest
  per (metric, tenant) for ``ttft`` / ``tpot`` / ``queue_wait`` /
  ``e2e``, per-tenant goodput + burn windows + token / KV-page-second
  cost counters, and a replica-wide rolling TPOT digest for skew.
  Tenant = the request's quota bucket (defaults to its LoRA adapter
  name, PR 13); base-model traffic aggregates under ``"-"``.
- :func:`fleet_rollup` — merge N trackers' wire-format shards
  (:meth:`SLOTracker.digests_dict`) into one exact fleet view; the
  Router's ``GET /stats`` and ``Server.stats()`` both build their
  payload through this one function, so single-server and fleet
  records are merge-consistent by construction.

Cost model (the PR 1/8 bar): every mutating entry point checks
``monitor.enabled()`` first — with ``FLAGS_enable_monitor`` off the
instrumented serving paths pay one bool branch and nothing else. With
it on, an observation is two ``math.log10`` calls and a couple of dict
pokes under an uncontended lock.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import enabled as _monitor_enabled

__all__ = [
    "LatencyDigest", "RollingDigest", "SLOPolicy", "SLOTracker",
    "SLO_METRICS", "fleet_rollup", "tenant_key", "ALL_TENANTS",
]

# the serving latency families one tracker digests, per tenant
SLO_METRICS = ("ttft", "tpot", "queue_wait", "e2e")

# label value base-model / un-tenanted traffic aggregates under (a
# tenant is normally a LoRA adapter name; None has no label form)
DEFAULT_TENANT = "-"
# the cross-tenant aggregate key in percentile/rollup views: the merge
# of every tenant's digest for a metric (exact — same bucketization)
ALL_TENANTS = "*"


def tenant_key(tenant: Optional[str]) -> str:
    """Normalize a tenant identity to its label/dict key (None/empty →
    ``"-"``, the base-traffic bucket)."""
    return tenant if tenant else DEFAULT_TENANT


class LatencyDigest:
    """Streaming latency digest over fixed log-spaced buckets.

    Bucket ``k`` (1-based) covers ``(lo * r**(k-1), lo * r**k]`` with
    ``r = 10 ** (1 / buckets_per_decade)``; bucket 0 is the underflow
    bin (``<= lo``) and bucket ``n+1`` the overflow bin (``> hi``).
    The boundaries depend only on the config, so two digests with the
    SAME config merge exactly: elementwise counter add, and every
    percentile read off the merge equals the percentile of the
    concatenated observation streams at digest resolution.

    :meth:`percentile` returns the UPPER edge of the bucket holding the
    requested rank (clamped into the observed [min, max]), so the
    estimate is conservative and within one bucket width — a factor of
    ``r`` (~15.5% at the default 16 buckets/decade) — of the true
    order statistic, for values inside [lo, hi]. Values outside the
    range land in the open under/overflow bins where only the exact
    tracked min/max bound them; size [lo, hi] to the latency family
    (the defaults span 0.1 ms .. 1000 s).
    """

    __slots__ = ("lo", "hi", "bpd", "n", "counts", "count", "sum",
                 "min", "max", "_log_lo")

    def __init__(self, lo: float = 1e-4, hi: float = 1e3,
                 buckets_per_decade: int = 16):
        if not (0 < lo < hi):
            raise ValueError(f"need 0 < lo < hi, got lo={lo!r} hi={hi!r}")
        if buckets_per_decade < 1:
            raise ValueError(
                f"buckets_per_decade must be >= 1, got "
                f"{buckets_per_decade!r}")
        self.lo = float(lo)
        self.hi = float(hi)
        self.bpd = int(buckets_per_decade)
        self.n = max(1, math.ceil(
            self.bpd * (math.log10(self.hi) - math.log10(self.lo))
            - 1e-9))
        self.counts = [0] * (self.n + 2)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._log_lo = math.log10(self.lo)

    # -- config / identity ---------------------------------------------------
    @property
    def config(self) -> Tuple[float, float, int]:
        return (self.lo, self.hi, self.bpd)

    def _index(self, value: float) -> int:
        if value <= self.lo:
            return 0
        if value > self.hi:
            return self.n + 1
        # bucket k covers (lo*r^(k-1), lo*r^k]: ceil of the log offset
        k = math.ceil((math.log10(value) - self._log_lo) * self.bpd
                      - 1e-12)
        return min(max(k, 1), self.n)

    def _upper(self, idx: int) -> float:
        """Upper edge of bucket ``idx`` (the percentile estimate)."""
        if idx <= 0:
            return self.lo
        if idx >= self.n + 1:
            return self.max if self.max is not None else self.hi
        return self.lo * (10.0 ** (idx / self.bpd))

    # -- mutation ------------------------------------------------------------
    def observe(self, value: float) -> None:
        value = float(value)
        self.counts[self._index(value)] += 1
        self.count += 1
        self.sum += value
        if self.min is None or value < self.min:
            self.min = value
        if self.max is None or value > self.max:
            self.max = value

    def merge(self, other: "LatencyDigest") -> "LatencyDigest":
        """Merge ``other`` into self (exact: identical configs add
        counter-by-counter). Returns self for chaining."""
        if other.config != self.config:
            raise ValueError(
                f"cannot merge digests with different configs: "
                f"{self.config} vs {other.config} — fleet digests must "
                f"share one bucketization for the merge to be exact")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        if other.min is not None and (self.min is None
                                      or other.min < self.min):
            self.min = other.min
        if other.max is not None and (self.max is None
                                      or other.max > self.max):
            self.max = other.max
        return self

    # -- reads ---------------------------------------------------------------
    def percentile(self, q: float) -> Optional[float]:
        """The q-th percentile estimate (upper bucket edge, clamped to
        the observed [min, max]); None on an empty digest."""
        if self.count == 0:
            return None
        rank = min(self.count, max(1, math.ceil(q / 100.0 * self.count)))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                ub = self._upper(i)
                if self.max is not None:
                    ub = min(ub, self.max)
                if self.min is not None:
                    ub = max(ub, self.min)
                return ub
        return self.max   # unreachable when counters are consistent

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def summary(self) -> Dict[str, Any]:
        """Compact human/JSON view: count/mean/max + p50/p90/p99."""
        return {
            "count": self.count,
            "mean": (round(self.mean, 6)
                     if self.count else None),
            "max": (round(self.max, 6) if self.max is not None
                    else None),
            "p50": (round(self.percentile(50), 6)
                    if self.count else None),
            "p90": (round(self.percentile(90), 6)
                    if self.count else None),
            "p99": (round(self.percentile(99), 6)
                    if self.count else None),
        }

    # -- wire format (the /stats merge path) ---------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {"lo": self.lo, "hi": self.hi, "bpd": self.bpd,
                "count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max,
                "counts": list(self.counts)}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LatencyDigest":
        out = cls(lo=d["lo"], hi=d["hi"], buckets_per_decade=d["bpd"])
        counts = list(d["counts"])
        if len(counts) != len(out.counts):
            raise ValueError(
                f"digest wire dict has {len(counts)} buckets, config "
                f"implies {len(out.counts)}")
        out.counts = [int(c) for c in counts]
        out.count = int(d["count"])
        out.sum = float(d["sum"])
        out.min = None if d.get("min") is None else float(d["min"])
        out.max = None if d.get("max") is None else float(d["max"])
        return out


class _EpochWindow:
    """Sliding-window substrate shared by :class:`RollingDigest` and
    the burn-rate counters: the window is sharded into ``shards``
    epoch-aligned cells; a touch lands in the current epoch's cell and
    cells older than the window expire WHOLESALE on the next access —
    O(1) amortized, no per-sample timestamps. One implementation, one
    expiry semantics (a snapshot spans up to ``window_s`` +- one shard
    of granularity), however the cell contents differ."""

    __slots__ = ("shard_s", "shards", "_cell_factory", "_cells")

    def __init__(self, window_s: float, shards: int, cell_factory):
        if not window_s > 0 or shards < 1:
            raise ValueError(
                f"need window_s > 0 and shards >= 1, got "
                f"{window_s!r}/{shards!r}")
        self.shard_s = float(window_s) / int(shards)
        self.shards = int(shards)
        self._cell_factory = cell_factory
        self._cells: Dict[int, Any] = {}

    def _prune(self, epoch: int) -> None:
        cut = epoch - self.shards + 1
        for e in [e for e in self._cells if e < cut]:
            del self._cells[e]

    def cell(self, now: Optional[float] = None):
        """The current epoch's cell (created on first touch)."""
        now = time.monotonic() if now is None else now
        epoch = int(now // self.shard_s)
        self._prune(epoch)
        c = self._cells.get(epoch)
        if c is None:
            c = self._cells.setdefault(epoch, self._cell_factory())
        return c

    def live(self, now: Optional[float] = None) -> list:
        """Every cell still inside the window."""
        now = time.monotonic() if now is None else now
        self._prune(int(now // self.shard_s))
        return list(self._cells.values())


class RollingDigest:
    """A :class:`LatencyDigest` over a sliding time window (an
    :class:`_EpochWindow` of digest cells). :meth:`snapshot` merges
    the live shards (exact — same config), so a percentile read
    reflects the last ``window_s``-ish seconds (granularity: one
    shard, ``window_s / shards``)."""

    def __init__(self, window_s: float = 30.0, shards: int = 6,
                 **digest_kw):
        self.window_s = float(window_s)
        self._kw = dict(digest_kw)
        self._win = _EpochWindow(window_s, shards,
                                 lambda: LatencyDigest(**self._kw))

    def observe(self, value: float,
                now: Optional[float] = None) -> None:
        self._win.cell(now).observe(value)

    def snapshot(self, now: Optional[float] = None) -> LatencyDigest:
        """Merged digest over the live window (may be empty)."""
        out = LatencyDigest(**self._kw)
        for d in self._win.live(now):
            out.merge(d)
        return out


class SLOPolicy:
    """Per-request latency SLO: thresholds + goodput target.

    A request MEETS the SLO when every configured threshold holds for
    it (``ttft_p99_s``: time to first token; ``tpot_p99_s``: per-token
    decode cadence; ``e2e_p99_s``: end to end). The *_p99 naming states
    the operating intent — run the fleet so the p99 stays under the
    threshold, i.e. goodput >= ``goodput_target`` — while the verdict
    itself is per request (that is what makes goodput a simple met/total
    fraction that merges exactly across replicas). A metric a request
    has no value for (a 1-token request has no TPOT) is skipped, not
    missed; a request that FAILED misses by definition.

    ``burn_rate`` is the SRE alerting shape: miss fraction over a
    window divided by the budget fraction ``1 - goodput_target``.
    Burn > 1 means the window spends error budget faster than the
    target accrues it; the fast window (default 60 s) catches a cliff,
    the slow one (default 600 s) a smolder."""

    def __init__(self, ttft_p99_s: Optional[float] = None,
                 tpot_p99_s: Optional[float] = None,
                 e2e_p99_s: Optional[float] = None,
                 goodput_target: float = 0.99,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 600.0):
        if ttft_p99_s is None and tpot_p99_s is None \
                and e2e_p99_s is None:
            raise ValueError(
                "SLOPolicy needs at least one threshold "
                "(ttft_p99_s / tpot_p99_s / e2e_p99_s)")
        for name, v in (("ttft_p99_s", ttft_p99_s),
                        ("tpot_p99_s", tpot_p99_s),
                        ("e2e_p99_s", e2e_p99_s)):
            if v is not None and not v > 0:
                raise ValueError(f"{name} must be > 0 or None, got {v!r}")
        if not 0.0 < goodput_target < 1.0:
            raise ValueError(
                f"goodput_target must be in (0, 1), got "
                f"{goodput_target!r}")
        if not 0 < fast_window_s <= slow_window_s:
            raise ValueError(
                f"need 0 < fast_window_s <= slow_window_s, got "
                f"{fast_window_s!r}/{slow_window_s!r}")
        self.ttft_p99_s = ttft_p99_s
        self.tpot_p99_s = tpot_p99_s
        self.e2e_p99_s = e2e_p99_s
        self.goodput_target = goodput_target
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s

    def misses(self, ttft_s: Optional[float], tpot_s: Optional[float],
               e2e_s: Optional[float]) -> List[str]:
        """Which configured dimensions this request missed (empty =
        SLO met). ``None`` values are not-applicable, never a miss."""
        out = []
        if self.ttft_p99_s is not None and ttft_s is not None \
                and ttft_s > self.ttft_p99_s:
            out.append("ttft")
        if self.tpot_p99_s is not None and tpot_s is not None \
                and tpot_s > self.tpot_p99_s:
            out.append("tpot")
        if self.e2e_p99_s is not None and e2e_s is not None \
                and e2e_s > self.e2e_p99_s:
            out.append("e2e")
        return out

    def burn_rate(self, met: int, missed: int) -> Optional[float]:
        """Error-budget burn over a window's (met, missed) counts;
        None on an empty window."""
        total = met + missed
        if not total:
            return None
        return (missed / total) / (1.0 - self.goodput_target)

    def to_dict(self) -> Dict[str, Any]:
        return {"ttft_p99_s": self.ttft_p99_s,
                "tpot_p99_s": self.tpot_p99_s,
                "e2e_p99_s": self.e2e_p99_s,
                "goodput_target": self.goodput_target,
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s}


class _Window:
    """Rolling (met, missed) pair over the shared epoch-shard window
    — the burn-rate substrate. Caller provides locking (the
    tracker's)."""

    __slots__ = ("_win",)

    def __init__(self, window_s: float, shards: int = 6):
        self._win = _EpochWindow(window_s, shards, lambda: [0, 0])

    def add(self, met: bool, now: Optional[float] = None) -> None:
        self._win.cell(now)[0 if met else 1] += 1

    def counts(self, now: Optional[float] = None) -> Tuple[int, int]:
        cells = self._win.live(now)
        return (sum(c[0] for c in cells), sum(c[1] for c in cells))


def _blank_tenant() -> Dict[str, Any]:
    return {"requests": 0, "met": 0, "missed": 0, "failed": 0,
            "tokens": 0, "kv_page_seconds": 0.0}


def _round_opt(v: Optional[float], nd: int = 4) -> Optional[float]:
    return None if v is None else round(v, nd)


def _tenant_record(counters: Dict[str, Any],
                   policy: Optional[SLOPolicy],
                   fast: Tuple[int, int],
                   slow: Tuple[int, int]) -> Dict[str, Any]:
    """The ONE per-tenant record builder every surface shares —
    ``load()``'s slo block, ``Server.stats()``, and the fleet rollup.
    Goodput is met/(met+missed) (None before any scored request);
    burn rates divide each window's miss fraction by the policy's
    error budget. A semantics change lands here once and every
    surface moves together (the can't-drift rule)."""
    rec = dict(counters)
    rec["kv_page_seconds"] = round(rec.get("kv_page_seconds", 0.0), 3)
    if policy is not None:
        total = counters["met"] + counters["missed"]
        rec["goodput"] = (round(counters["met"] / total, 4)
                          if total else None)
        rec["burn_fast"] = _round_opt(policy.burn_rate(*fast))
        rec["burn_slow"] = _round_opt(policy.burn_rate(*slow))
    return rec


class SLOTracker:
    """Per-server SLO/goodput aggregation (one per ``serving.Server``).

    Written by the scheduler thread (observes/records), read by
    healthz/router/stats threads — every mutation and read holds one
    small internal lock, never across engine work, so reads stay
    lock-light the way ``Server.load()`` promises. Every mutating
    entry point no-ops while ``FLAGS_enable_monitor`` is off (the
    near-zero disabled path; the scheduler's call sites branch on
    ``monitor.enabled()`` too, so the off path pays ONE bool check).

    ``policy=None`` still digests latencies and accounts per-tenant
    cost (tokens, KV-page-seconds) — goodput/burn need a policy, the
    digests and the skew detector's rolling TPOT do not."""

    def __init__(self, policy: Optional[SLOPolicy] = None,
                 window_s: float = 30.0,
                 lo: float = 1e-4, hi: float = 1e3,
                 buckets_per_decade: int = 16):
        if policy is not None and not isinstance(policy, SLOPolicy):
            raise ValueError(
                f"policy must be an SLOPolicy or None, got {policy!r}")
        self.policy = policy
        self.window_s = float(window_s)
        self._kw = dict(lo=lo, hi=hi,
                        buckets_per_decade=buckets_per_decade)
        self._lock = threading.Lock()
        self._dig: Dict[Tuple[str, str], LatencyDigest] = {}
        # replica-wide rolling TPOT: what the fleet skew detector reads
        self._roll = RollingDigest(window_s=window_s, **self._kw)
        self._ten: Dict[str, Dict[str, Any]] = {}
        self._fast: Dict[str, _Window] = {}
        self._slow: Dict[str, _Window] = {}

    # -- mutation (scheduler thread) -----------------------------------------
    def _digest(self, metric: str, tenant: str) -> LatencyDigest:
        d = self._dig.get((metric, tenant))
        if d is None:
            d = self._dig.setdefault((metric, tenant),
                                     LatencyDigest(**self._kw))
        return d

    def observe(self, metric: str, tenant: Optional[str],
                value: float) -> None:
        """One latency observation (``metric`` in :data:`SLO_METRICS`).
        No-op while the monitor is disabled."""
        if not _monitor_enabled():
            return
        t = tenant_key(tenant)
        with self._lock:
            self._digest(metric, t).observe(value)
            if metric == "tpot":
                self._roll.observe(value)

    def record_finish(self, tenant: Optional[str],
                      ttft_s: Optional[float],
                      tpot_s: Optional[float], e2e_s: float,
                      n_tokens: int, kv_page_seconds: float = 0.0
                      ) -> Tuple[bool, List[str]]:
        """Record one FINISHED request: digests its tpot/e2e (ttft and
        queue_wait were observed at their edges), applies the policy
        verdict, and accounts tokens + KV-page-seconds to its tenant.
        Returns ``(met, missed_dimensions)`` so the caller can emit
        monitor counters; ``(True, [])`` while disabled or policy-free.
        """
        if not _monitor_enabled():
            return True, []
        t = tenant_key(tenant)
        misses: List[str] = []
        if self.policy is not None:
            misses = self.policy.misses(ttft_s, tpot_s, e2e_s)
        met = not misses
        with self._lock:
            if tpot_s is not None:
                self._digest("tpot", t).observe(tpot_s)
                self._roll.observe(tpot_s)
            self._digest("e2e", t).observe(e2e_s)
            ten = self._ten.setdefault(t, _blank_tenant())
            ten["requests"] += 1
            ten["tokens"] += int(n_tokens)
            ten["kv_page_seconds"] += float(kv_page_seconds)
            if self.policy is not None:
                ten["met" if met else "missed"] += 1
                self._window(t).add(met)
                self._window(t, slow=True).add(met)
        return met, misses

    def record_failure(self, tenant: Optional[str]) -> None:
        """A request the service failed to deliver (FAILED terminal):
        an SLO miss by definition. Cancelled/expired requests are
        client verdicts and are NOT recorded."""
        if not _monitor_enabled():
            return
        t = tenant_key(tenant)
        with self._lock:
            ten = self._ten.setdefault(t, _blank_tenant())
            ten["requests"] += 1
            ten["failed"] += 1
            if self.policy is not None:
                ten["missed"] += 1
                self._window(t).add(False)
                self._window(t, slow=True).add(False)

    def _window(self, tenant: str, slow: bool = False) -> _Window:
        store = self._slow if slow else self._fast
        w = store.get(tenant)
        if w is None:
            span = (self.policy.slow_window_s if slow
                    else self.policy.fast_window_s)
            w = store.setdefault(tenant, _Window(span))
        return w

    # -- reads (any thread) --------------------------------------------------
    def goodput(self, tenant: Optional[str] = None) -> Optional[float]:
        """Lifetime goodput for one tenant (or the aggregate over all,
        ``tenant=None``...naming the default bucket needs ``"-"``);
        None without a policy or before any scored request."""
        if self.policy is None:
            return None
        with self._lock:
            if tenant is None:
                met = sum(v["met"] for v in self._ten.values())
                missed = sum(v["missed"] for v in self._ten.values())
            else:
                ten = self._ten.get(tenant_key(tenant))
                if ten is None:
                    return None
                met, missed = ten["met"], ten["missed"]
        total = met + missed
        return met / total if total else None

    def rolling_tpot_p50(self, min_count: int = 1) -> Optional[float]:
        """Rolling-window TPOT p50 (replica-wide, all tenants) — the
        skew detector's input. None until ``min_count`` observations
        sit in the window (a starved replica must read unknown, not
        fast)."""
        with self._lock:
            snap = self._roll.snapshot()
        if snap.count < max(1, min_count):
            return None
        return snap.percentile(50)

    def tenant_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant counters + goodput/burn (policy permitting) —
        the ``/healthz`` ``slo`` block's tenants table."""
        with self._lock:
            tens = {t: dict(v) for t, v in self._ten.items()}
            windows = {}
            if self.policy is not None:
                for t in tens:
                    windows[t] = (self._fast[t].counts()
                                  if t in self._fast else (0, 0),
                                  self._slow[t].counts()
                                  if t in self._slow else (0, 0))
        out = {}
        for t, v in tens.items():
            fast, slow = windows.get(t, ((0, 0), (0, 0)))
            out[t] = _tenant_record(v, self.policy, fast, slow)
        return out

    def percentiles(self) -> Dict[str, Dict[str, Dict[str, Any]]]:
        """{metric: {tenant: summary}} including the exact all-tenants
        aggregate under ``"*"`` (a digest merge, not an average)."""
        with self._lock:
            out: Dict[str, Dict[str, Dict[str, Any]]] = {}
            aggs: Dict[str, LatencyDigest] = {}
            for (metric, t), d in self._dig.items():
                out.setdefault(metric, {})[t] = d.summary()
                agg = aggs.get(metric)
                if agg is None:
                    aggs[metric] = agg = LatencyDigest(**self._kw)
                agg.merge(d)
            for metric, agg in aggs.items():
                out[metric][ALL_TENANTS] = agg.summary()
        return out

    def snapshot(self) -> Optional[Dict[str, Any]]:
        """Compact host-side view for ``Server.load()``/``/healthz``:
        policy, per-tenant goodput/burn/cost, and the headline p50/p99s
        per tenant. None while nothing has been recorded (an idle or
        monitor-off server adds no ``slo`` block)."""
        tens = self.tenant_stats()
        with self._lock:
            have_dig = bool(self._dig)
        if not tens and not have_dig:
            return None
        out: Dict[str, Any] = {"window_s": self.window_s,
                               "tenants": tens}
        if self.policy is not None:
            out["policy"] = self.policy.to_dict()
        with self._lock:
            for metric in ("ttft", "tpot"):
                per = {}
                for (m, t), d in self._dig.items():
                    if m == metric and d.count:
                        per[t] = {"p50": round(d.percentile(50), 6),
                                  "p99": round(d.percentile(99), 6),
                                  "count": d.count}
                if per:
                    out[metric] = per
        return out

    def digests_dict(self) -> Dict[str, Any]:
        """The mergeable WIRE format: everything a fleet rollup needs
        to reconstruct this server's contribution exactly — digests per
        (metric, tenant), the rolling TPOT digest, per-tenant counters,
        and the burn-window (met, missed) counts. Pure host data
        (JSON-serializable), the shape a future remote replica ships
        over HTTP."""
        with self._lock:
            metrics: Dict[str, Dict[str, Any]] = {}
            for (metric, t), d in self._dig.items():
                metrics.setdefault(metric, {})[t] = d.to_dict()
            out = {
                "config": dict(self._kw, window_s=self.window_s),
                "policy": (self.policy.to_dict()
                           if self.policy is not None else None),
                "metrics": metrics,
                "rolling_tpot": self._roll.snapshot().to_dict(),
                "tenants": {t: dict(v) for t, v in self._ten.items()},
                "windows": {
                    t: {"fast": list(self._fast[t].counts())
                        if t in self._fast else [0, 0],
                        "slow": list(self._slow[t].counts())
                        if t in self._slow else [0, 0]}
                    for t in self._ten} if self.policy is not None
                else {},
            }
        return out


def fleet_rollup(shards: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge N :meth:`SLOTracker.digests_dict` shards into one EXACT
    fleet view — the ``GET /stats`` payload body.

    Percentiles come from digest MERGES (identical bucketization →
    elementwise add → the merged digest is the digest of the
    concatenated streams); goodput/burn come from SUMMED met/missed
    counters. Both are exact merge semantics: no percentile averaging,
    no rate-of-averages. ``Server.stats()`` is a 1-shard rollup through
    this same function, so single-server and fleet records can never
    drift in shape or math."""
    merged: Dict[Tuple[str, str], LatencyDigest] = {}
    tenants: Dict[str, Dict[str, Any]] = {}
    windows: Dict[str, Dict[str, List[int]]] = {}
    policy_d: Optional[Dict[str, Any]] = None
    window_s: Optional[float] = None
    for sh in shards:
        if not sh:
            continue
        if policy_d is None:
            policy_d = sh.get("policy")
        if window_s is None:
            window_s = (sh.get("config") or {}).get("window_s")
        for metric, per_t in (sh.get("metrics") or {}).items():
            for t, dd in per_t.items():
                d = LatencyDigest.from_dict(dd)
                cur = merged.get((metric, t))
                if cur is None:
                    merged[(metric, t)] = d
                else:
                    cur.merge(d)
        for t, v in (sh.get("tenants") or {}).items():
            ten = tenants.setdefault(t, _blank_tenant())
            for k in ("requests", "met", "missed", "failed", "tokens"):
                ten[k] += int(v.get(k, 0))
            ten["kv_page_seconds"] += float(v.get("kv_page_seconds",
                                                  0.0))
        for t, w in (sh.get("windows") or {}).items():
            dst = windows.setdefault(t, {"fast": [0, 0],
                                         "slow": [0, 0]})
            for span in ("fast", "slow"):
                pair = w.get(span) or [0, 0]
                dst[span][0] += int(pair[0])
                dst[span][1] += int(pair[1])
    policy = (SLOPolicy(**policy_d)
              if policy_d and any(
                  policy_d.get(k) is not None
                  for k in ("ttft_p99_s", "tpot_p99_s", "e2e_p99_s"))
              else None)
    metrics: Dict[str, Dict[str, Dict[str, Any]]] = {}
    aggs: Dict[str, LatencyDigest] = {}
    for (metric, t), d in merged.items():
        metrics.setdefault(metric, {})[t] = d.summary()
        agg = aggs.get(metric)
        if agg is None:
            aggs[metric] = LatencyDigest(lo=d.lo, hi=d.hi,
                                         buckets_per_decade=d.bpd
                                         ).merge(d)
        else:
            agg.merge(d)
    for metric, agg in aggs.items():
        metrics[metric][ALL_TENANTS] = agg.summary()
    tstats: Dict[str, Dict[str, Any]] = {}
    for t, v in tenants.items():
        w = windows.get(t, {"fast": [0, 0], "slow": [0, 0]})
        tstats[t] = _tenant_record(v, policy, tuple(w["fast"]),
                                   tuple(w["slow"]))
    return {"policy": policy_d, "window_s": window_s,
            "tenants": tstats, "metrics": metrics}
