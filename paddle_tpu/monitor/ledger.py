"""paddle_tpu.monitor.ledger — process-wide compiled-program ledger.

PR 15 tells an operator *whether* serving is slow (goodput/burn) and
PR 8 *which request phase* was slow (trace decomposition); THIS module
answers the remaining question — *which compiled program* is eating
the step, and how far it sits from the hardware roofline.

Every :func:`paddle_tpu.monitor.monitored_jit` program (engine
prefill/chunk/admit/segment/spec/quant/lora-install programs,
``to_static`` graphs, bench drivers) registers here under a **stable
program id** — ``<name>:<hash>`` where the hash covers the entry-point
name, the flattened arg treedef, every array leaf's aval
(shape/dtype) + sharding spec, and the repr of non-array (static)
leaves. The id is a pure function of that signature: the same program
gets the same id across process restarts, replicas, and replay — which
is what lets a Router merge per-replica ledgers exactly and lets
``bench_diff`` line up two rounds (MIGRATING.md bullet).

Per program the ledger holds:

- XLA ``cost_analysis()`` at first sight — FLOPs, bytes accessed,
  output bytes (``jitted.lower(...).cost_analysis()``: trace+lower
  only, no second backend compile) — plus donated-argument bytes where
  the jit wrapper declared donation;
- compile count + compile wall seconds (the ``monitored_jit`` miss
  path attributes them per program id, so warmup cost is attributable
  and a zero-post-warmup-compiles assertion can NAME the violator);
- a per-program :class:`~paddle_tpu.monitor.slo.LatencyDigest` of
  host-observed dispatch walls (one fixed bucketization → replica
  ledgers MERGE exactly, the PR 15 property, for free). The compiling
  call's wall is excluded from the digest — a 2 s compile inside a
  1 ms program's latency distribution would be a lie — and charged to
  compile seconds instead.

From these it derives achieved FLOP/s and bytes/s (total work over
total digest seconds), arithmetic intensity (FLOPs / bytes — a program
property), MFU against the per-backend peak table
(:mod:`paddle_tpu.device.peaks`) and the roofline verdict:
intensity below the machine balance → memory-bound, above →
compute-bound.

Cost model — the PR 15 one-bool bar: with ``FLAGS_enable_ledger`` off
every dispatch pays exactly one extra bool branch inside
``monitored_jit``. On, a dispatch pays one arg-signature flatten
(O(leaves) tuple build), one dict hit, one digest observe and two
counter bumps — ``serve_bench --profile-ab`` keeps the measured TPOT
overhead ≤ 1.05x. Cost analysis, peak calibration, and lowering happen
once per program, never per dispatch.

Ownership & retirement: engines pass ``owner=<engine label>`` into
``monitored_jit``; ``release(owner)`` (called from ``engine.close()``)
drops every program whose LAST owner retired and removes its
``{program=...}`` monitor series — the ``TestSeriesRetirement``
contract extended to the ledger. Ownerless programs (``to_static``,
bench drivers) are process-lifetime by design. The per-program
``paddle_tpu_jit_cache_miss_total{fn,program}`` compile counters are
process-wide compile HISTORY and intentionally survive engine close.
"""
from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set

from .slo import LatencyDigest

__all__ = [
    "enable", "disable", "enabled", "reset",
    "program_id", "record", "release", "owned_programs",
    "profile", "merge_profiles",
    "DISPATCH_COUNTER", "SECONDS_COUNTER", "MFU_GAUGE",
]

# one fixed digest config for every program digest — identical
# bucketization is what makes cross-replica merges exact. Dispatch
# walls span ~µs (tiny admit programs on CPU) to minutes (big compiles
# excluded, but cold first segments on real models are seconds).
_DIGEST_KW = dict(lo=1e-6, hi=1e3, buckets_per_decade=16)

DISPATCH_COUNTER = "paddle_tpu_program_dispatches_total"
SECONDS_COUNTER = "paddle_tpu_program_seconds_total"
MFU_GAUGE = "paddle_tpu_program_mfu"

_enabled = False     # synced from FLAGS_enable_ledger below
_lock = threading.Lock()
_records: Dict[str, "_ProgramRecord"] = {}
_owners: Dict[str, Set[str]] = {}    # pid -> live owner labels
_peaks: Optional[Dict[str, Any]] = None


class _ProgramRecord:
    __slots__ = ("pid", "name", "signature", "owners_seen", "flops",
                 "bytes_accessed", "output_bytes", "donated_bytes",
                 "arg_bytes", "compiles", "compile_seconds",
                 "dispatches", "digest")

    def __init__(self, pid: str, name: str, signature: str):
        self.pid = pid
        self.name = name
        self.signature = signature
        self.owners_seen: Set[str] = set()
        self.flops: Optional[float] = None
        self.bytes_accessed: Optional[float] = None
        self.output_bytes: Optional[float] = None
        self.donated_bytes: Optional[int] = None
        self.arg_bytes: Optional[int] = None
        self.compiles = 0
        self.compile_seconds = 0.0
        self.dispatches = 0
        self.digest = LatencyDigest(**_DIGEST_KW)

    # -- wire format (what /profile serves; what Router merges) -------------
    def to_dict(self) -> Dict[str, Any]:
        return {
            "program": self.pid, "name": self.name,
            "signature": self.signature,
            "owners": sorted(self.owners_seen),
            "flops": self.flops, "bytes_accessed": self.bytes_accessed,
            "output_bytes": self.output_bytes,
            "donated_bytes": self.donated_bytes,
            "arg_bytes": self.arg_bytes,
            "compiles": self.compiles,
            "compile_seconds": round(self.compile_seconds, 6),
            "dispatches": self.dispatches,
            "digest": self.digest.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "_ProgramRecord":
        rec = cls(d["program"], d.get("name", d["program"]),
                  d.get("signature", ""))
        rec.owners_seen = set(d.get("owners") or ())
        for f in ("flops", "bytes_accessed", "output_bytes",
                  "donated_bytes", "arg_bytes"):
            setattr(rec, f, d.get(f))
        rec.compiles = int(d.get("compiles", 0))
        rec.compile_seconds = float(d.get("compile_seconds", 0.0))
        rec.dispatches = int(d.get("dispatches", 0))
        if d.get("digest"):
            rec.digest = LatencyDigest.from_dict(d["digest"])
        return rec

    def merge(self, other: "_ProgramRecord") -> "_ProgramRecord":
        """Exact cross-shard merge (same pid → same program → identical
        cost analysis; counters add, digests add bucketwise)."""
        self.owners_seen |= other.owners_seen
        for f in ("flops", "bytes_accessed", "output_bytes",
                  "donated_bytes", "arg_bytes"):
            if getattr(self, f) is None:
                setattr(self, f, getattr(other, f))
        self.compiles += other.compiles
        self.compile_seconds += other.compile_seconds
        self.dispatches += other.dispatches
        self.digest.merge(other.digest)
        return self


# -- enable / disable --------------------------------------------------------


def enabled() -> bool:
    return _enabled


def _sync_enabled(value: bool) -> None:
    """Flag push target (framework.flags.set_flags): flips the one
    fast-path bool ``monitored_jit`` branches on; enabling also warms
    the peak cache so per-dispatch MFU never calibrates on a serving
    path."""
    global _enabled
    _enabled = bool(value)
    if _enabled:
        _ensure_peaks()


def enable() -> None:
    """Turn the ledger on (equivalent to
    ``set_flags({"FLAGS_enable_ledger": True})``)."""
    from ..framework.flags import set_flags

    set_flags({"FLAGS_enable_ledger": True})


def disable() -> None:
    from ..framework.flags import set_flags

    set_flags({"FLAGS_enable_ledger": False})


def reset() -> None:
    """Drop every program record and owner binding (the per-arm bench
    idiom, next to ``monitor.reset()``); peak cache survives."""
    with _lock:
        pids = list(_records)
        _records.clear()
        _owners.clear()
    for pid in pids:
        _retire_series(pid)


def _ensure_peaks() -> Optional[Dict[str, Any]]:
    global _peaks
    with _lock:
        if _peaks is not None:
            return _peaks
    try:
        from ..device import peaks as peaks_mod

        rec = peaks_mod.peaks()
    except Exception:
        rec = None
    with _lock:
        if _peaks is None:
            _peaks = rec
        return _peaks


# -- program identity --------------------------------------------------------


def _leaf_sig(x: Any) -> str:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        sh = getattr(x, "sharding", None)
        spec = getattr(sh, "spec", None)
        core = f"{dtype}{list(shape)}"
        return f"{core}@{spec}" if spec is not None else core
    if isinstance(x, (int, float, bool, str, bytes, type(None))):
        return repr(x)
    return f"{type(x).__name__}:{x!r}"


def program_id(name: str, args: Sequence[Any],
               kwargs: Dict[str, Any]) -> str:
    """Stable program id for one (entry point, arg signature): the
    entry-point name plus a short blake2b over the flattened treedef
    and every leaf's aval/sharding (arrays) or repr (statics). Pure
    function of the call signature — identical across restarts,
    replicas, and replay."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((tuple(args), kwargs))
    canon = "|".join([name, str(treedef)]
                     + [_leaf_sig(x) for x in leaves])
    h = hashlib.blake2b(canon.encode(), digest_size=4).hexdigest()
    return f"{name}:{h}"


def _human_sig(args: Sequence[Any], kwargs: Dict[str, Any]) -> str:
    """Short human-readable signature for the profile table (array
    avals only — statics are in the id hash but would bloat a table)."""
    import jax

    leaves, _ = jax.tree_util.tree_flatten((tuple(args), kwargs))
    parts = []
    for x in leaves:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(f"{dtype}{list(shape)}")
        if len(parts) >= 8:
            parts.append("...")
            break
    return " ".join(parts)


# -- recording (called by monitored_jit, ledger-enabled path only) -----------


def _cost_analysis(jitted, args, kwargs) -> Dict[str, Optional[float]]:
    """FLOPs / bytes accessed / output bytes from XLA's lowered cost
    analysis. ``lower()`` traces + lowers only (no backend compile) —
    cheap enough to pay once per program at registration. Any failure
    degrades to Nones: the ledger must never take a dispatch down."""
    out: Dict[str, Optional[float]] = {
        "flops": None, "bytes_accessed": None, "output_bytes": None}
    try:
        ca = jitted.lower(*args, **kwargs).cost_analysis() or {}
        if "flops" in ca:
            out["flops"] = float(ca["flops"])
        if "bytes accessed" in ca:
            out["bytes_accessed"] = float(ca["bytes accessed"])
        out_bytes = 0.0
        seen_out = False
        for k, v in ca.items():
            # per-shape-index output keys vary across jax versions
            # ("bytes accessed output", "bytes accessedout{}", ...)
            if k.startswith("bytes accessed") and "out" in k[14:]:
                out_bytes += float(v)
                seen_out = True
        if seen_out:
            out["output_bytes"] = out_bytes
    except Exception:
        pass
    return out


def record(pid: str, name: str, owner: Optional[str], jitted,
           args: Sequence[Any], kwargs: Dict[str, Any], dt: float,
           compiled: bool, donate: Sequence[int] = ()) -> None:
    """One dispatch of program ``pid``: register on first sight
    (cost analysis + donated/arg bytes), count the dispatch, feed the
    digest (non-compile calls only), bump the ``{program=...}`` series.
    Called by ``monitored_jit`` only while the ledger is enabled."""
    if not _enabled:
        return
    with _lock:
        rec = _records.get(pid)
        is_new = rec is None
        if is_new:
            rec = _records[pid] = _ProgramRecord(
                pid, name, _human_sig(args, kwargs))
        if owner:
            rec.owners_seen.add(owner)
            _owners.setdefault(pid, set()).add(owner)
        rec.dispatches += 1
        if compiled:
            rec.compiles += 1
            rec.compile_seconds += dt
        else:
            rec.digest.observe(dt)
    if is_new:
        # outside the ledger lock: lowering can take seconds on big
        # models and must not block other programs' dispatch recording
        cost = _cost_analysis(jitted, args, kwargs)
        arg_bytes = 0
        donated_bytes = 0
        try:
            import jax

            leaves, _ = jax.tree_util.tree_flatten(
                (tuple(args), kwargs))
            arg_bytes = sum(int(x.nbytes) for x in leaves
                            if hasattr(x, "nbytes"))
            for i in donate:
                if 0 <= i < len(args):
                    d_leaves, _ = jax.tree_util.tree_flatten(args[i])
                    donated_bytes += sum(int(x.nbytes) for x in d_leaves
                                         if hasattr(x, "nbytes"))
        except Exception:
            pass
        with _lock:
            rec2 = _records.get(pid)
            if rec2 is not None:
                rec2.flops = cost["flops"]
                rec2.bytes_accessed = cost["bytes_accessed"]
                rec2.output_bytes = cost["output_bytes"]
                rec2.arg_bytes = arg_bytes
                rec2.donated_bytes = donated_bytes or None
    if _enabled:   # series bumps (monitor no-ops them when IT is off)
        from . import counter, gauge

        counter(DISPATCH_COUNTER,
                "ledger: dispatches per compiled program "
                "(compiling calls included)",
                ("program",)).labels(program=pid).inc()
        counter(SECONDS_COUNTER,
                "ledger: host-observed dispatch wall seconds per "
                "compiled program (compile walls excluded — see "
                "paddle_tpu_jit_compile_seconds_total{program})",
                ("program",)).labels(program=pid).inc(
                    0.0 if compiled else dt)
        pk = _peaks
        flops = rec.flops
        if (not compiled and pk is not None and flops
                and dt > 0):
            gauge(MFU_GAUGE,
                  "ledger: model FLOP utilization of the LATEST "
                  "dispatch vs the backend peak table",
                  ("program",)).labels(program=pid).set(
                      round(flops / dt / pk["peak_flops"], 6))


# -- ownership / retirement --------------------------------------------------


def _retire_series(pid: str) -> None:
    from . import remove_series

    for series in (DISPATCH_COUNTER, SECONDS_COUNTER, MFU_GAUGE):
        try:
            remove_series(series, program=pid)
        except Exception:
            pass


def release(owner: str) -> int:
    """Retire one owner (engine) label: programs whose LAST live owner
    this was are dropped from the ledger and their ``{program=...}``
    series removed — the ``TestSeriesRetirement`` contract. Programs
    still co-owned (a twin replica serving the same model) or ownerless
    (``to_static``; process-lifetime) are untouched. Returns programs
    dropped. Idempotent."""
    dropped: List[str] = []
    with _lock:
        for pid in list(_owners):
            live = _owners[pid]
            if owner in live:
                live.discard(owner)
                if not live:
                    del _owners[pid]
                    _records.pop(pid, None)
                    dropped.append(pid)
    for pid in dropped:
        _retire_series(pid)
    return len(dropped)


def owned_programs(owner: str) -> List[str]:
    """Program ids currently owned by ``owner`` (test/debug surface)."""
    with _lock:
        return sorted(pid for pid, live in _owners.items()
                      if owner in live)


# -- read side ---------------------------------------------------------------


def _derived(d: Dict[str, Any], pk: Optional[Dict[str, Any]]
             ) -> Dict[str, Any]:
    """Roofline-derived view of one wire record: achieved FLOP/s and
    bytes/s over the digest's total seconds, arithmetic intensity, MFU
    and bandwidth utilization vs the backend peaks, and the verdict —
    intensity under the machine balance is memory-bound."""
    dig = LatencyDigest.from_dict(d["digest"])
    out = dict(d)
    out["summary"] = dig.summary()
    total_s = dig.sum
    out["total_seconds"] = round(total_s, 6)
    flops = d.get("flops")
    byts = d.get("bytes_accessed")
    if flops and byts:
        out["intensity"] = round(flops / byts, 4)
    else:
        out["intensity"] = None
    if total_s > 0 and dig.count:
        if flops:
            out["achieved_flops_per_s"] = flops * dig.count / total_s
        if byts:
            out["achieved_bytes_per_s"] = byts * dig.count / total_s
    if pk:
        af = out.get("achieved_flops_per_s")
        ab = out.get("achieved_bytes_per_s")
        if af:
            out["mfu"] = round(af / pk["peak_flops"], 6)
        if ab:
            out["bw_util"] = round(ab / pk["peak_bytes_per_s"], 6)
        if out["intensity"] is not None:
            out["bound"] = ("memory-bound"
                            if out["intensity"] < pk["machine_balance"]
                            else "compute-bound")
    return out


def profile(owners: Optional[Sequence[str]] = None,
            top_k: Optional[int] = None,
            derived: bool = True) -> Dict[str, Any]:
    """The ledger snapshot — what ``Server.profile()`` / ``GET
    /profile`` serve and what :func:`merge_profiles` merges::

        {"programs": {pid: <record wire dict [+ derived roofline
                            fields when derived=True]>},
         "peaks": <device peak record or None>,
         "top": [pid, ...]   # by total digest seconds, descending
         "total_seconds": <sum over programs>}

    ``owners`` filters to programs owned by any of the given engine
    labels (a Server scopes to its engine; None = the whole process).
    ``top_k`` truncates ``top`` (the table everyone reads first);
    ``programs`` always carries every matching record, because a
    truncated shard would make the Router's fleet merge WRONG."""
    pk = _ensure_peaks() if derived else None
    with _lock:
        recs = list(_records.values())
        own = {p: set(s) for p, s in _owners.items()}
    if owners is not None:
        want = set(owners)
        recs = [r for r in recs
                if own.get(r.pid, set()) & want or r.owners_seen & want]
    wire = {r.pid: r.to_dict() for r in recs}
    if derived:
        wire = {pid: _derived(d, pk) for pid, d in wire.items()}
    totals = {pid: (d["total_seconds"] if derived
                    else LatencyDigest.from_dict(d["digest"]).sum)
              for pid, d in wire.items()}
    top = sorted(totals, key=lambda p: -totals[p])
    if top_k is not None:
        top = top[:top_k]
    return {"programs": wire, "peaks": pk, "top": top,
            "total_seconds": round(sum(totals.values()), 6)}


def merge_profiles(shards: Sequence[Optional[Dict[str, Any]]],
                   top_k: Optional[int] = None) -> Dict[str, Any]:
    """EXACT fleet merge of per-replica :func:`profile` shards — the
    ``fleet_rollup`` idiom applied to program records: same program id
    → counters add, digests add bucketwise (identical fixed
    bucketization), cost analysis taken from the first shard that has
    it. Never an average of percentiles. ``None``/empty shards (a
    mid-restart replica) are skipped."""
    merged: Dict[str, _ProgramRecord] = {}
    pk = None
    for shard in shards:
        if not shard:
            continue
        if pk is None:
            pk = shard.get("peaks")
        for pid, d in (shard.get("programs") or {}).items():
            rec = _ProgramRecord.from_dict(d)
            if pid in merged:
                merged[pid].merge(rec)
            else:
                merged[pid] = rec
    wire = {pid: _derived(r.to_dict(), pk)
            for pid, r in merged.items()}
    top = sorted(wire, key=lambda p: -wire[p]["total_seconds"])
    if top_k is not None:
        top = top[:top_k]
    return {"programs": wire, "peaks": pk, "top": top,
            "total_seconds": round(
                sum(d["total_seconds"] for d in wire.values()), 6)}


# -- flag sync (import-time): FLAGS_enable_ledger may already be set via
#    the environment; importing the module honors it ------------------------
def _init_from_flags():
    from ..framework.flags import get_flags

    _sync_enabled(bool(
        get_flags("FLAGS_enable_ledger")["FLAGS_enable_ledger"]))


_init_from_flags()
