"""paddle_tpu.monitor — always-on, low-overhead runtime metrics.

The profiler (``paddle_tpu.profiler``) answers "where did this traced
window go" with spans; THIS package answers "what is the framework doing
right now" with a process-wide metrics registry (reference analog: the
profiler_statistic.py aggregate tables + Paddle's monitor/stat registry,
paddle/fluid/platform/monitor.h StatRegistry — but pull-based and cheap
enough to leave on in production serving).

Three instrument kinds, all label-aware and lock-protected:

- :class:`Counter` — monotonically increasing (ops dispatched, tokens
  generated, jit cache misses);
- :class:`Gauge` — point-in-time value, settable or computed at collect
  time via :func:`register_callback` (HBM bytes, KV-page occupancy,
  dataloader queue depth);
- :class:`Histogram` — bucketed distribution with sum/count (op latency,
  step time, dataloader wait, admission latency).

Cost model: every mutating call checks one module-level bool first, so
with ``FLAGS_enable_monitor`` off the instrumented hot paths pay a
branch and nothing else — and the per-op hook is NOT installed at all
(``core.op_hooks.op_span_hook`` stays ``None`` unless the profiler owns
it). Collection (:func:`snapshot`, :func:`render_prometheus`,
:func:`write_jsonl`) is pull-based: callback gauges (device memory,
live-array bytes) are only evaluated when someone asks.

Enable via ``FLAGS_enable_monitor=1`` in the environment,
``paddle_tpu.set_flags({"FLAGS_enable_monitor": True})``, or
:func:`enable` / :func:`disable` here.

Export surfaces:

- :func:`snapshot` — nested dict (name → type/help/samples);
- :func:`render_prometheus` — Prometheus text exposition format 0.0.4;
- :func:`write_jsonl` — one ``{"metric":…, "value":…, "labels":…}``
  line per sample, the same shape as the ``BENCH_*.json`` trajectory
  records, so bench tooling reads both;
- :func:`start_http_server` — stdlib ThreadingHTTPServer serving
  ``/metrics`` (Prometheus) and ``/metrics.json`` (snapshot).
"""
from __future__ import annotations

import bisect
import functools
import itertools
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram",
    "counter", "gauge", "histogram", "register_callback",
    "enable", "disable", "enabled",
    "snapshot", "render_prometheus", "write_jsonl", "reset",
    "remove_series",
    "start_http_server", "http_payload", "monitored_jit",
    "instance_label",
    "install_op_hook", "uninstall_op_hook",
]

from ..core import op_hooks as _op_hooks  # dependency-free leaf module

_instance_counters: Dict[str, "itertools.count"] = {}
_instance_lock = threading.Lock()


def instance_label(prefix: str) -> str:
    """Process-unique label value for one instrument-owning instance
    (``pool0``, ``loader3``, ``engine1`` …) — the shared idiom for
    gauges that would otherwise be clobbered across instances. Owners
    should ``remove()`` their series when the instance retires."""
    with _instance_lock:
        c = _instance_counters.setdefault(prefix, itertools.count())
        return f"{prefix}{next(c)}"

_lock = threading.RLock()
_REGISTRY: Dict[str, "_MetricBase"] = {}
_CALLBACKS: Dict[str, Tuple[str, Callable[[], Any]]] = {}
_enabled = False  # synced from FLAGS_enable_monitor below

# default buckets span sub-µs op dispatch to multi-second compiles
DEFAULT_BUCKETS = (
    1e-6, 5e-6, 1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)


def _label_key(labelnames: Sequence[str], labels: Dict[str, str]
               ) -> Tuple[str, ...]:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} do not match declared labelnames "
            f"{sorted(labelnames)}")
    return tuple(str(labels[n]) for n in labelnames)


class _MetricBase:
    kind = "untyped"

    def __init__(self, name: str, help_: str = "",
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    # -- labels ------------------------------------------------------------
    def labels(self, **labels):
        return _Bound(self, _label_key(self.labelnames, labels))

    def _unlabeled(self) -> Tuple[str, ...]:
        if self.labelnames:
            raise ValueError(
                f"{self.name} declares labels {self.labelnames}; use "
                f".labels(...)")
        return ()

    def remove(self, **labels) -> None:
        """Drop one label combination's series (idempotent) — owners of
        per-instance labels retire them here so dead instances don't
        export stale values forever."""
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values.pop(key, None)

    def clear(self):
        raise NotImplementedError


class _Bound:
    """A metric bound to one label-value combination; proxies the
    mutators so call sites read ``m.labels(op="matmul").observe(dt)``."""

    __slots__ = ("_m", "_key")

    def __init__(self, metric, key):
        self._m = metric
        self._key = key

    def inc(self, amount: float = 1.0):
        self._m._inc(self._key, amount)

    def dec(self, amount: float = 1.0):
        self._m._inc(self._key, -amount)

    def set(self, value: float):
        self._m._set(self._key, value)

    def observe(self, value: float):
        self._m._observe(self._key, value)

    @property
    def value(self):
        return self._m._get(self._key)


class Counter(_MetricBase):
    kind = "counter"

    def __init__(self, name, help_="", labelnames=()):
        super().__init__(name, help_, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def _inc(self, key, amount):
        if amount < 0:
            # validate BEFORE the enabled fast-path: a negative inc is a
            # call-site bug and must fail identically whether the
            # monitor is on or off (not only once ops enable it)
            raise ValueError(f"counter {self.name} cannot decrease")
        if not _enabled:
            return
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def inc(self, amount: float = 1.0):
        self._inc(self._unlabeled(), amount)

    def _get(self, key):
        with self._lock:
            return self._values.get(key, 0.0)

    @property
    def value(self) -> float:
        return self._get(self._unlabeled())

    def clear(self):
        with self._lock:
            self._values.clear()

    def _samples(self):
        with self._lock:
            return [(k, v) for k, v in self._values.items()]


class Gauge(_MetricBase):
    kind = "gauge"

    def __init__(self, name, help_="", labelnames=()):
        super().__init__(name, help_, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def _set(self, key, value):
        if not _enabled:
            return
        with self._lock:
            self._values[key] = float(value)

    def _inc(self, key, amount):
        if not _enabled:
            return
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def set(self, value: float):
        self._set(self._unlabeled(), value)

    def inc(self, amount: float = 1.0):
        self._inc(self._unlabeled(), amount)

    def dec(self, amount: float = 1.0):
        self._inc(self._unlabeled(), -amount)

    def _get(self, key):
        with self._lock:
            return self._values.get(key, 0.0)

    @property
    def value(self) -> float:
        return self._get(self._unlabeled())

    def clear(self):
        with self._lock:
            self._values.clear()

    def _samples(self):
        with self._lock:
            return [(k, v) for k, v in self._values.items()]


class Histogram(_MetricBase):
    kind = "histogram"

    def __init__(self, name, help_="", labelnames=(),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_, labelnames)
        self.buckets = tuple(sorted(buckets))
        # key -> [bucket_counts(list, len(buckets)+1 incl +Inf), sum, count]
        self._values: Dict[Tuple[str, ...], list] = {}

    def _observe(self, key, value):
        if not _enabled:
            return
        value = float(value)
        with self._lock:
            st = self._values.get(key)
            if st is None:
                st = [[0] * (len(self.buckets) + 1), 0.0, 0]
                self._values[key] = st
            # bisect over the sorted bounds: buckets[i-1] < v <= buckets[i]
            st[0][bisect.bisect_left(self.buckets, value)] += 1
            st[1] += value
            st[2] += 1

    def observe(self, value: float):
        self._observe(self._unlabeled(), value)

    def _get(self, key):
        with self._lock:
            st = self._values.get(key)
            if st is None:
                return {"count": 0, "sum": 0.0, "buckets": {}}
            cum = 0
            buckets = {}
            for i, ub in enumerate(self.buckets):
                cum += st[0][i]
                buckets[ub] = cum
            return {"count": st[2], "sum": st[1], "buckets": buckets}

    @property
    def value(self):
        return self._get(self._unlabeled())

    def clear(self):
        with self._lock:
            self._values.clear()

    def _samples(self):
        with self._lock:
            keys = list(self._values)
        return [(k, self._get(k)) for k in keys]


# -- registry ---------------------------------------------------------------


def _get_or_create(cls, name, help_, labelnames, **kw):
    with _lock:
        m = _REGISTRY.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            if tuple(labelnames) != m.labelnames:
                raise ValueError(
                    f"metric {name!r} registered with labelnames "
                    f"{m.labelnames}, requested {tuple(labelnames)}")
            return m
        m = cls(name, help_, labelnames, **kw)
        _REGISTRY[name] = m
        return m


def counter(name: str, help_: str = "", labelnames: Sequence[str] = ()
            ) -> Counter:
    return _get_or_create(Counter, name, help_, labelnames)


def gauge(name: str, help_: str = "", labelnames: Sequence[str] = ()
          ) -> Gauge:
    return _get_or_create(Gauge, name, help_, labelnames)


def histogram(name: str, help_: str = "", labelnames: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return _get_or_create(Histogram, name, help_, labelnames,
                          buckets=buckets)


def register_callback(name: str, help_: str,
                      fn: Callable[[], Any]) -> None:
    """Register a pull-time gauge: ``fn`` runs at collect time and
    returns either a scalar or a list of ``(labels_dict, value)``.
    Exceptions inside ``fn`` drop that metric from the collection (a
    broken probe must not take snapshot() down with it)."""
    with _lock:
        _CALLBACKS[name] = (help_, fn)


def reset() -> None:
    """Zero every registered metric's values (the metric objects and
    callbacks stay registered — instrument modules hold references)."""
    with _lock:
        for m in _REGISTRY.values():
            m.clear()


def remove_series(name: str, **match) -> int:
    """Drop every label combination of metric ``name`` whose labels
    include ``match`` as a subset (idempotent; unknown metrics are a
    no-op). The instance-retirement idiom for metrics with OPEN label
    dimensions — an engine owning ``{engine=engineN, bucket=*}`` series
    can't enumerate the bucket values it emitted, so it retires by the
    ``engine`` label alone. Returns the number of series removed."""
    with _lock:
        metric = _REGISTRY.get(name)
    if metric is None:
        return 0
    removed = 0
    with metric._lock:
        for key in list(metric._values):
            labels = dict(zip(metric.labelnames, key))
            if all(labels.get(k) == v for k, v in match.items()):
                metric._values.pop(key, None)
                removed += 1
    return removed


# -- enable / disable -------------------------------------------------------


def enabled() -> bool:
    return _enabled


def _sync_enabled(value: bool) -> None:
    """Flag push target (framework.flags.set_flags) — flips the fast-path
    bool and installs/uninstalls the per-op hook."""
    global _enabled
    _enabled = bool(value)
    if _enabled:
        install_op_hook()
    else:
        uninstall_op_hook()


def enable() -> None:
    """Turn the monitor on (equivalent to
    ``set_flags({"FLAGS_enable_monitor": True})``)."""
    from ..framework.flags import set_flags

    set_flags({"FLAGS_enable_monitor": True})


def disable() -> None:
    from ..framework.flags import set_flags

    set_flags({"FLAGS_enable_monitor": False})


# -- per-op instrumentation (core.op_hooks choke point) ---------------------

_op_hist: Optional[Histogram] = None
_op_children: Dict[str, _Bound] = {}  # op name -> bound series (fast path)
_chained_prev: Optional[Callable[[str, int, int], None]] = None
_in_chain = False  # True while _op_span_hook is reachable from the slot


def _op_span_hook(name: str, start_ns: int, end_ns: int) -> None:
    prev = _chained_prev
    if _enabled:
        if _op_hist is not None:
            # cache the bound series per op: this runs on EVERY eager
            # dispatch, so skip labels()'s set comparison + allocations
            child = _op_children.get(name)
            if child is None:
                child = _op_children.setdefault(
                    name, _op_hist.labels(op=name))
            child.observe((end_ns - start_ns) / 1e9)
    else:
        # disabled but still dispatched: either a profiler stop()
        # restored us into the slot after disable() couldn't reach it
        # (self-evict now so the state converges to a hook-free slot),
        # or we are still buried under a live profiler (forward only —
        # don't pay an uninstall attempt per op until we CAN evict).
        if _op_hooks.op_span_hook is _op_span_hook:
            uninstall_op_hook()  # prev was captured above: event still
    if prev is not None:         # reaches the chain below us
        prev(name, start_ns, end_ns)


def install_op_hook() -> None:
    """Install the per-op latency histogram on the apply_op choke point,
    chaining to whatever hook was already there (the profiler chains the
    same way, so spans and histograms fan out from one dispatch).

    Idempotent via ``_in_chain``: once our hook is reachable from the
    slot — even buried under a profiler hook that captured it as its
    prev — installing again must be a no-op, or we would chain to a
    hook that already chains to us and every op dispatch would recurse
    forever."""
    global _op_hist, _chained_prev, _in_chain
    from ..core import op_hooks

    if _in_chain or op_hooks.op_span_hook is _op_span_hook:
        return
    if _op_hist is None:
        _op_hist = histogram(
            "paddle_tpu_op_latency_seconds",
            "eager apply_op dispatch latency (host wall time) per op",
            ("op",))
    _chained_prev = op_hooks.skip_dead(op_hooks.op_span_hook)
    op_hooks.op_span_hook = _op_span_hook
    _in_chain = True


def uninstall_op_hook() -> None:
    """Remove the monitor hook when the slot is ours. If another
    consumer (the profiler) installed on top of us, the CHAIN is left
    intact — our hook no-ops while disabled, keeps forwarding to the
    hook below, and a later enable() just flips the bool back
    (``_in_chain`` stays True so no second copy is ever chained in)."""
    global _chained_prev, _in_chain
    from ..core import op_hooks

    if op_hooks.op_span_hook is _op_span_hook:
        # restore the chain below us, minus hooks from profiler windows
        # that stopped while we sat on top of them (they are inert but
        # restoring one would leave the slot non-None forever)
        op_hooks.op_span_hook = op_hooks.skip_dead(_chained_prev)
        _chained_prev = None
        _in_chain = False


# -- jit compile tracker ----------------------------------------------------


def monitored_jit(fn: Optional[Callable] = None, *, name: Optional[str] = None,
                  owner: Optional[str] = None, **jit_kwargs):
    """``jax.jit`` wrapper that counts cache misses and compile seconds
    per PROGRAM and feeds the program ledger.

    A miss is detected by the traced body actually running (jax only
    re-enters the Python function when the (shape, dtype, static-arg)
    signature is new); the wall time of that call — trace + lower +
    compile — is charged to ``paddle_tpu_jit_compile_seconds_total``.
    Both miss counters carry a ``program`` label alongside ``fn``: one
    entry point compiles many programs (a prefill per bucket width, a
    spec step per k), and attributing warmup cost per program is what
    lets a zero-post-warmup-compiles assertion NAME the violator.

    The program id (``<name>:<hash>`` over treedef + avals + sharding +
    static reprs — see :func:`ledger.program_id`) is memoized per arg
    signature, so a cache hit computes one cheap signature tuple and
    one dict lookup, not a hash. Cache hits with monitor AND ledger off
    pay one bool check over plain ``jax.jit``.

    ``owner`` ties every program this wrapper creates to an engine
    label so ``engine.close()`` → ``ledger.release(owner)`` can retire
    its ledger rows and series; ownerless wrappers (``to_static``,
    bench drivers) register process-lifetime programs. Usable as a
    decorator or called directly; ``name`` labels the metrics (defaults
    to the function's __name__)."""
    def wrap(fn):
        import jax

        from . import ledger as _ledger

        label = name or getattr(fn, "__name__", "jit")
        # thread-local: jax traces in the CALLING thread, so per-thread
        # flags keep concurrent servers from cross-attributing misses
        missed = threading.local()
        variants: Dict[Any, str] = {}   # cheap arg-sig -> program id
        donate = jit_kwargs.get("donate_argnums", ())
        if isinstance(donate, int):
            donate = (donate,)

        @functools.wraps(fn)
        def traced(*a, **k):
            missed.flag = True
            return fn(*a, **k)

        jitted = jax.jit(traced, **jit_kwargs)

        def _pid(a, k):
            leaves, treedef = jax.tree_util.tree_flatten((a, k))
            sig = (treedef, tuple(
                (x.shape, str(x.dtype)) if hasattr(x, "shape")
                and hasattr(x, "dtype")
                else x if isinstance(x, (int, float, bool, str,
                                         bytes, type(None)))
                else repr(x)
                for x in leaves))
            pid = variants.get(sig)
            if pid is None:
                pid = _ledger.program_id(label, a, k)
                variants[sig] = pid
            return pid

        @functools.wraps(fn)
        def call(*a, **k):
            if not (_enabled or _ledger._enabled):
                return jitted(*a, **k)
            missed.flag = False
            t0 = time.perf_counter()
            out = jitted(*a, **k)
            was_miss = missed.flag
            led = _ledger._enabled
            if not (was_miss or led):
                return out
            dt = time.perf_counter() - t0
            pid = _pid(a, k)
            if was_miss and _enabled:
                counter("paddle_tpu_jit_cache_miss_total",
                        "jit traces+compiles (cache misses) per entry "
                        "point and program",
                        ("fn", "program")).labels(
                            fn=label, program=pid).inc()
                counter("paddle_tpu_jit_compile_seconds_total",
                        "wall seconds spent tracing+compiling per entry "
                        "point and program",
                        ("fn", "program")).labels(
                            fn=label, program=pid).inc(dt)
            if led:
                _ledger.record(pid, label, owner, jitted, a, k, dt,
                               was_miss, donate)
            return out

        call._jitted = jitted  # escape hatch: .lower / cache inspection
        call._program_ids = variants  # pids seen so far, by arg sig
        return call

    return wrap(fn) if fn is not None else wrap


def jit_miss_by_fn(snap: Optional[dict] = None) -> Dict[str, float]:
    """Cache-miss counts summed per entry point (``fn`` label) — the
    pre-PR 16 per-fn view of ``paddle_tpu_jit_cache_miss_total``, for
    callers/tests that don't care which program of an entry point
    compiled. Pass a ``snapshot()`` to diff two moments."""
    snap = snapshot() if snap is None else snap
    out: Dict[str, float] = {}
    m = snap.get("metrics", {}).get("paddle_tpu_jit_cache_miss_total")
    for rec in (m or {}).get("samples", []):
        fn = rec["labels"].get("fn", "?")
        out[fn] = out.get(fn, 0.0) + rec["value"]
    return out


# -- built-in callback gauges: HBM / live arrays ----------------------------


def _collect_memory():
    """Device memory samples: XLA allocator stats per device (TPU/GPU).
    ``memory_stats()`` is None on CPU backends — there the live-array
    total stands in (kind="live_array_bytes"), so the metric is never
    empty and dashboards work unchanged across backends."""
    import jax

    out = []
    for d in jax.local_devices():
        ms = d.memory_stats() or {}
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if k in ms:
                out.append(({"device": f"{d.platform}:{d.id}", "kind": k},
                            float(ms[k])))
    if not out:
        out.append(({"device": "host", "kind": "live_array_bytes"},
                    _collect_live_bytes()))
    return out


_live_bytes_memo = [0.0, -1.0]  # (value, monotonic ts)


def _collect_live_bytes():
    """Σ nbytes over live jax.Arrays, memoized for 200ms: one snapshot
    evaluates this for both paddle_tpu_live_array_bytes and the CPU
    hbm_bytes fallback, and the O(live arrays) walk should run once per
    scrape, not once per metric."""
    import jax

    now = time.monotonic()
    if now - _live_bytes_memo[1] > 0.2:
        _live_bytes_memo[0] = float(
            sum(a.nbytes for a in jax.live_arrays()))
        _live_bytes_memo[1] = now
    return _live_bytes_memo[0]


register_callback(
    "paddle_tpu_hbm_bytes",
    "XLA allocator stats per local device (absent on CPU backends)",
    _collect_memory)
register_callback(
    "paddle_tpu_live_array_bytes",
    "total bytes of live jax.Arrays in this process (HBM high-water "
    "proxy that also works on CPU)",
    _collect_live_bytes)


# -- collection / export ----------------------------------------------------


def _callback_samples():
    out = {}
    with _lock:
        cbs = list(_CALLBACKS.items())
    for name, (help_, fn) in cbs:
        try:
            val = fn()
        except Exception:
            continue  # a broken probe must not break collection
        if isinstance(val, (int, float)):
            samples = [({}, float(val))]
        else:
            samples = [(dict(lbl), float(v)) for lbl, v in val]
        out[name] = (help_, samples)
    return out


def snapshot() -> Dict[str, Any]:
    """One coherent read of every metric: ``{"ts": …, "metrics": {name:
    {"type", "help", "samples": [{"labels", …}]}}}``. Histograms carry
    count/sum/mean and cumulative buckets per sample."""
    metrics: Dict[str, Any] = {}
    with _lock:
        regs = list(_REGISTRY.items())
    for name, m in regs:
        samples = []
        for key, val in m._samples():
            labels = dict(zip(m.labelnames, key))
            if m.kind == "histogram":
                samples.append({
                    "labels": labels, "count": val["count"],
                    "sum": val["sum"],
                    "mean": (val["sum"] / val["count"]
                             if val["count"] else 0.0),
                    "buckets": {str(k): v
                                for k, v in val["buckets"].items()},
                })
            else:
                samples.append({"labels": labels, "value": val})
        metrics[name] = {"type": m.kind, "help": m.help,
                         "samples": samples}
    for name, (help_, samples) in _callback_samples().items():
        metrics[name] = {
            "type": "gauge", "help": help_,
            "samples": [{"labels": lbl, "value": v}
                        for lbl, v in samples],
        }
    return {"ts": time.time(), "metrics": metrics}


def _prom_escape(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _prom_labels(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_prom_escape(v)}"' for k, v in labels.items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_prometheus() -> str:
    """Prometheus text exposition format 0.0.4 of the full registry."""
    snap = snapshot()
    lines: List[str] = []
    for name, meta in sorted(snap["metrics"].items()):
        # HELP escaping per exposition format 0.0.4: \ and newline only
        help_ = str(meta["help"]).replace("\\", r"\\").replace("\n",
                                                               r"\n")
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {meta['type']}")
        for s in meta["samples"]:
            if meta["type"] == "histogram":
                for le, n in s["buckets"].items():
                    le_lbl = 'le="%s"' % le
                    lines.append(
                        f"{name}_bucket"
                        f"{_prom_labels(s['labels'], le_lbl)} {n}")
                inf_lbl = 'le="+Inf"'
                lines.append(
                    f"{name}_bucket"
                    f"{_prom_labels(s['labels'], inf_lbl)}"
                    f" {s['count']}")
                lines.append(f"{name}_sum{_prom_labels(s['labels'])}"
                             f" {_fmt(s['sum'])}")
                lines.append(f"{name}_count{_prom_labels(s['labels'])}"
                             f" {s['count']}")
            else:
                lines.append(f"{name}{_prom_labels(s['labels'])}"
                             f" {_fmt(s['value'])}")
    return "\n".join(lines) + "\n"


_UNIT_SUFFIXES = (
    ("_seconds_total", "s"), ("_seconds", "s"), ("_bytes", "bytes"),
    ("_per_sec", "1/s"), ("_ratio", "ratio"), ("_total", "count"),
    # serving-layer families (queue depth / in-flight request gauges)
    ("_depth", "reqs"), ("_requests", "reqs"),
)


def _unit_for(name: str) -> Optional[str]:
    for suffix, unit in _UNIT_SUFFIXES:
        if name.endswith(suffix):
            return unit
    return None


def write_jsonl(path: str, extra: Optional[Dict[str, Any]] = None) -> int:
    """Append one JSON line per sample to ``path`` — the same
    ``{"metric": …, "value": …, "unit": …}`` record shape the BENCH_*
    trajectory uses, plus ``labels`` and the snapshot timestamp.
    Histograms emit their count/sum/mean. Returns lines written."""
    snap = snapshot()
    n = 0
    with open(path, "a") as f:
        for name, meta in sorted(snap["metrics"].items()):
            for s in meta["samples"]:
                rec: Dict[str, Any] = {"metric": name, "ts": snap["ts"]}
                if meta["type"] == "histogram":
                    rec["value"] = s["mean"]
                    rec["count"] = s["count"]
                    rec["sum"] = s["sum"]
                else:
                    rec["value"] = s["value"]
                unit = _unit_for(name)
                if unit:
                    rec["unit"] = unit
                if s["labels"]:
                    rec["labels"] = s["labels"]
                if extra:
                    rec.update(extra)
                f.write(json.dumps(rec) + "\n")
                n += 1
    return n


def http_payload(path: str) -> Optional[Tuple[bytes, str]]:
    """(body, content_type) for the monitor's HTTP endpoints —
    ``/metrics.json`` (snapshot) and ``/metrics`` (Prometheus text) —
    or None for any other path. The ONE place the export payloads are
    built; every front-end (:func:`start_http_server`, the serving
    package's HTTP server) serves these bytes."""
    if path.startswith("/metrics.json"):
        return json.dumps(snapshot()).encode(), "application/json"
    if path.startswith("/metrics"):
        return (render_prometheus().encode(),
                "text/plain; version=0.0.4; charset=utf-8")
    return None


def start_http_server(port: int = 0, addr: str = "127.0.0.1"):
    """Serve ``/metrics`` (Prometheus text) and ``/metrics.json``
    (snapshot) on a daemon thread; returns the server (its bound port is
    ``server.server_address[1]`` — port=0 picks a free one)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            payload = http_payload(self.path)
            if payload is None:
                self.send_response(404)
                self.end_headers()
                return
            body, ctype = payload
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # no access-log spam on stderr
            pass

    server = ThreadingHTTPServer((addr, port), Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="paddle_tpu-monitor-http")
    t.start()
    return server


# -- flag sync (import-time): FLAGS_enable_monitor may already be set via
#    the environment; importing the monitor honors it ------------------------
def _init_from_flags():
    from ..framework.flags import get_flags

    _sync_enabled(get_flags("FLAGS_enable_monitor")["FLAGS_enable_monitor"])


_init_from_flags()
