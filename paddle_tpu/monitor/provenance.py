"""Environment provenance stamp for BENCH records and flight dumps.

A bench record or a flight-recorder dump is evidence; evidence without
a chain of custody is an anecdote. ``bench_diff`` comparing two rounds
is only sound when both ran the same backend on comparable machines —
so every BENCH record and every flight-recorder dump carries this
``env`` header and ``bench_diff`` warns when the headers disagree.

The stamp is computed ONCE per process and cached (the fields cannot
change mid-run; ``git rev-parse`` forks a subprocess, which must not
happen per record). Every field degrades to ``None`` rather than
raising — a missing git binary must not take a bench down.
"""
from __future__ import annotations

import os
import socket
import sys
import threading
from typing import Any, Dict, Optional

__all__ = ["env_stamp"]

_lock = threading.Lock()
_cache: Optional[Dict[str, Any]] = None


def _git_rev() -> Optional[str]:
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
            capture_output=True, timeout=5)
        if out.returncode == 0:
            return out.stdout.decode().strip() or None
    except Exception:
        pass
    return None


def env_stamp(extra: Optional[Dict[str, Any]] = None,
              refresh: bool = False) -> Dict[str, Any]:
    """The cached provenance header::

        {"jax", "python", "backend", "device_kind", "device_count",
         "hostname", "pid", "git_rev"}

    ``extra`` (e.g. ``{"tp_degree": 2}`` or a mesh shape) is merged
    into a COPY — the cache itself never mutates, so two callers with
    different extras cannot contaminate each other."""
    global _cache
    with _lock:
        cached = _cache
    if cached is None or refresh:
        stamp: Dict[str, Any] = {
            "jax": None, "python": sys.version.split()[0],
            "backend": None, "device_kind": None, "device_count": None,
            "hostname": socket.gethostname(), "pid": os.getpid(),
            "git_rev": _git_rev(),
        }
        try:
            import jax

            stamp["jax"] = jax.__version__
            devs = jax.devices()
            stamp["backend"] = devs[0].platform
            stamp["device_kind"] = getattr(devs[0], "device_kind",
                                           devs[0].platform)
            stamp["device_count"] = len(devs)
        except Exception:
            pass
        with _lock:
            _cache = stamp
        cached = stamp
    if extra:
        out = dict(cached)
        out.update(extra)
        return out
    return dict(cached)
