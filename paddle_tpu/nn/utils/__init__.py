"""paddle.nn.utils parity (reference: python/paddle/nn/utils/ —
weight/spectral norm hooks, parameter flattening, gradient clipping).
"""
from .utils import (clip_grad_norm_, clip_grad_value_,
                    parameters_to_vector, remove_weight_norm,
                    spectral_norm, vector_to_parameters, weight_norm)

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_", "clip_grad_value_"]
