"""nn.utils implementations.

weight_norm / spectral_norm reparameterize a layer's weight via a
forward pre-hook (reference weight_norm_hook.py:141 /
spectral_norm_hook.py:117): the hook recomputes `weight` from the
auxiliary parameters before every forward, so the optimizer trains
(weight_g, weight_v) / the norm sees power-iterated u,v — identical
training semantics, jit-friendly (plain jnp math per forward).
"""
from __future__ import annotations

import math
from typing import List

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ..parameter import Parameter

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_", "clip_grad_value_"]


def _norm_except_dim(v, dim):
    if dim is None:
        return jnp.sqrt(jnp.sum(v * v))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(v * v, axis=axes, keepdims=True))


class _WeightNormHook:
    def __init__(self, name, dim):
        self.name = name
        self.dim = dim

    def compute(self, layer):
        g = getattr(layer, self.name + "_g").value
        v = getattr(layer, self.name + "_v").value
        w = v * (g / jnp.maximum(_norm_except_dim(v, self.dim), 1e-12))
        object.__setattr__(layer, self.name, Tensor(w))

    def __call__(self, layer, inputs):
        self.compute(layer)
        return inputs


def weight_norm(layer, name: str = "weight", dim: int = 0):
    """Reparameterize ``layer.<name>`` as g * v/||v|| (reference
    weight_norm_hook.weight_norm). Returns the layer."""
    w = getattr(layer, name)
    wv = w.value
    g0 = _norm_except_dim(wv, dim)
    # replace the original Parameter with (g, v)
    del layer._parameters[name]
    layer.add_parameter(name + "_g", Parameter(jnp.asarray(g0)))
    layer.add_parameter(name + "_v", Parameter(jnp.asarray(wv)))
    hook = _WeightNormHook(name, dim)
    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_hook = (hook, handle, name)
    hook.compute(layer)
    return layer


def remove_weight_norm(layer, name: str = "weight"):
    """Fold g * v/||v|| back into a plain weight Parameter (reference
    remove_weight_norm)."""
    hook, handle, hname = layer._weight_norm_hook
    hook.compute(layer)
    w = getattr(layer, hname)
    handle.remove() if hasattr(handle, "remove") else None
    del layer._parameters[hname + "_g"]
    del layer._parameters[hname + "_v"]
    layer.add_parameter(hname, Parameter(jnp.asarray(
        w.value if isinstance(w, Tensor) else w)))
    del layer._weight_norm_hook
    return layer


class _SpectralNormHook:
    def __init__(self, name, n_power_iterations, eps, dim):
        self.name = name
        self.n = n_power_iterations
        self.eps = eps
        self.dim = dim

    def compute(self, layer, update_uv=True):
        w = getattr(layer, self.name + "_orig").value
        wm = jnp.moveaxis(w, self.dim, 0).reshape(w.shape[self.dim], -1)
        u = getattr(layer, self.name + "_u")
        v_buf = getattr(layer, self.name + "_v")
        uv = u.value if isinstance(u, Tensor) else jnp.asarray(u)
        vv = v_buf.value if isinstance(v_buf, Tensor) else jnp.asarray(
            v_buf)
        if update_uv and layer.training:
            for _ in range(self.n):
                vv = wm.T @ uv
                vv = vv / jnp.maximum(jnp.linalg.norm(vv), self.eps)
                uv = wm @ vv
                uv = uv / jnp.maximum(jnp.linalg.norm(uv), self.eps)
            u.set_value(uv)
            v_buf.set_value(vv)
        sigma = uv @ wm @ vv
        object.__setattr__(layer, self.name,
                           Tensor(w / jnp.maximum(sigma, self.eps)))

    def __call__(self, layer, inputs):
        self.compute(layer)
        return inputs


def spectral_norm(layer, name: str = "weight", n_power_iterations: int = 1,
                  eps: float = 1e-12, dim=None):
    """Spectral normalization w / sigma_max(w) with power iteration
    (reference spectral_norm_hook.spectral_norm)."""
    w = getattr(layer, name)
    wv = w.value
    if dim is None:
        dim = 1 if type(layer).__name__.startswith(
            ("Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose",
             "Linear")) else 0
    h = wv.shape[dim]
    wm = jnp.moveaxis(wv, dim, 0).reshape(h, -1)
    key = jax.random.PRNGKey(0)
    u0 = jax.random.normal(key, (h,), wv.dtype)
    u0 = u0 / jnp.maximum(jnp.linalg.norm(u0), eps)
    v0 = jax.random.normal(jax.random.PRNGKey(1), (wm.shape[1],), wv.dtype)
    v0 = v0 / jnp.maximum(jnp.linalg.norm(v0), eps)
    del layer._parameters[name]
    layer.add_parameter(name + "_orig", Parameter(jnp.asarray(wv)))
    # u, v are buffers, not parameters
    setattr(layer, name + "_u", Tensor(u0))
    setattr(layer, name + "_v", Tensor(v0))
    hook = _SpectralNormHook(name, n_power_iterations, eps, dim)
    layer.register_forward_pre_hook(hook)
    hook.compute(layer, update_uv=False)
    return layer


def parameters_to_vector(parameters, name=None):
    """Flatten parameters into one 1-D tensor (reference
    transform_parameters.parameters_to_vector)."""
    vals = [p.value.reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(vals))


def vector_to_parameters(vec, parameters, name=None):
    """Inverse of parameters_to_vector — writes slices back in-place."""
    v = vec.value if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(math.prod(p.shape)) if hasattr(math, "prod") else int(
            jnp.prod(jnp.asarray(p.shape)))
        p.set_value(v[off:off + n].reshape(p.shape).astype(p.value.dtype))
        off += n


def clip_grad_norm_(parameters, max_norm, norm_type: float = 2.0,
                    error_if_nonfinite: bool = False):
    """In-place global-norm gradient clip (reference clip_grad_norm_).
    Returns the total norm."""
    params = [parameters] if isinstance(parameters, Parameter) \
        else list(parameters)
    grads = [p.grad for p in params if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(g.value)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g.value.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            f"The total norm of {norm_type} order of the gradients is "
            "non-finite, so it cannot be clipped")
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-12), 1.0)
    for p in params:
        if p.grad is not None:
            p.grad.set_value(p.grad.value * scale.astype(
                p.grad.value.dtype))
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    """In-place elementwise gradient clip (reference clip_grad_value_)."""
    params = [parameters] if isinstance(parameters, Parameter) \
        else list(parameters)
    cv = abs(float(clip_value))
    for p in params:
        if p.grad is not None:
            p.grad.set_value(jnp.clip(p.grad.value, -cv, cv))
