"""Seq2seq decoding (reference: python/paddle/nn/decode.py —
BeamSearchDecoder + dynamic_decode over an RNN cell).

The decode loop is host-driven (like the reference dygraph path): each
step is traced compute, the while-condition is a host readback — decode
loops with data-dependent termination belong to the host, the per-step
math to XLA.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .layer.layers import Layer

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


def _v(x):
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


class BeamSearchDecoder:
    """Beam-search wrapper over a step cell (reference decode.py
    BeamSearchDecoder). cell(inputs, states) -> (outputs, new_states);
    ``output_fn`` projects cell outputs to vocabulary logits."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[batch, ...] -> [batch*beam, ...] (reference helper)."""
        v = _v(x)
        tiled = jnp.repeat(v[:, None], beam_size, axis=1)
        return Tensor(tiled.reshape((-1,) + v.shape[1:]))

    def initialize(self, initial_cell_states):
        states = jax.tree.map(
            lambda s: _v(self.tile_beam_merge_with_batch(Tensor(_v(s)),
                                                         self.beam_size)),
            initial_cell_states)
        some = jax.tree.leaves(states)[0]
        bb = some.shape[0]
        batch = bb // self.beam_size
        tokens = jnp.full((batch, self.beam_size), self.start_token,
                          jnp.int32)
        # only beam 0 is live initially (log prob 0; others -inf)
        log_probs = jnp.where(jnp.arange(self.beam_size)[None, :] == 0,
                              0.0, -1e9) * jnp.ones((batch, 1))
        finished = jnp.zeros((batch, self.beam_size), bool)
        return tokens, {"cell": states, "log_probs": log_probs,
                        "finished": finished}

    def step(self, time, inputs, states):
        cell_states = states["cell"]
        batch, beam = states["log_probs"].shape
        ids = _v(inputs).reshape(-1)
        step_in = self.embedding_fn(Tensor(ids)) if self.embedding_fn \
            else Tensor(ids)
        out, new_cell = self.cell(step_in, cell_states)
        logits = self.output_fn(out) if self.output_fn else out
        logits = _v(logits)
        V = logits.shape[-1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        logp = logp.reshape(batch, beam, V)
        # frozen beams: only end_token continues, at no cost
        frozen = states["finished"]
        cont = jnp.where(jnp.arange(V)[None, None, :] == self.end_token,
                         0.0, -1e9)
        logp = jnp.where(frozen[..., None], cont, logp)
        total = states["log_probs"][..., None] + logp
        flat = total.reshape(batch, beam * V)
        top_lp, top_idx = jax.lax.top_k(flat, beam)
        parent = top_idx // V
        token = top_idx % V
        new_finished = jnp.take_along_axis(frozen, parent, axis=1) | (
            token == self.end_token)

        def regather(s):
            sv = _v(s).reshape((batch, beam) + _v(s).shape[1:])
            idx = parent.reshape(parent.shape + (1,) * (sv.ndim - 2))
            out = jnp.take_along_axis(sv, idx, axis=1)
            return out.reshape((batch * beam,) + sv.shape[2:])

        new_cell = jax.tree.map(regather, new_cell)
        new_states = {"cell": new_cell, "log_probs": top_lp,
                      "finished": new_finished}
        outputs = {"token": token, "parent": parent,
                   "log_probs": top_lp}
        return outputs, new_states, Tensor(token), new_finished

    def finalize(self, outputs, final_states, sequence_lengths=None):
        """Backtrace the beam tree to token sequences [T, batch, beam]."""
        from .functional.sequence_loss import gather_tree

        ids = Tensor(jnp.stack([o["token"] for o in outputs]))
        parents = Tensor(jnp.stack([o["parent"] for o in outputs]))
        return gather_tree(ids, parents)


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run a decoder until every beam finishes or max_step_num (reference
    decode.py dynamic_decode)."""
    max_step_num = max_step_num or 100
    inputs, states = decoder.initialize(inits)
    step_outputs = []
    lengths = prev_fin = None
    for t in range(int(max_step_num)):
        outputs, states, inputs, finished = decoder.step(t, inputs, states)
        step_outputs.append(outputs)
        fin = np.asarray(_v(finished))
        if lengths is None:
            lengths = np.zeros(fin.shape, np.int64)
            prev_fin = np.zeros(fin.shape, bool)
        # a beam's length includes the step on which it emitted EOS: count
        # every step where it was not ALREADY finished
        lengths = np.where(prev_fin, lengths, t + 1)
        prev_fin = fin
        if fin.all():
            break
    final = decoder.finalize(step_outputs, states)
    out = final
    if not output_time_major:
        ov = _v(final)
        out = Tensor(jnp.moveaxis(ov, 0, 1))  # [batch, T, beam]
    if return_length:
        return out, Tensor(jnp.asarray(lengths))
    return out
