"""Gradient clipping (python/paddle/nn/clip.py parity).

Clip objects transform a list of (param, grad) pairs; HybridParallelClipGrad
(distributed) subclasses ClipGradByGlobalNorm to allreduce partial norms
across mesh axes.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_grad_norm_", "clip_grad_value_"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g.value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            v = g.value
            norm = jnp.sqrt(jnp.sum(v.astype(jnp.float32) ** 2))
            scale = jnp.where(norm > self.clip_norm, self.clip_norm / norm, 1.0)
            out.append((p, Tensor((v * scale).astype(v.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name
        self.auto_skip_clip = auto_skip_clip

    def _global_norm_sq(self, params_grads):
        total = jnp.zeros((), jnp.float32)
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            v = g.value.astype(jnp.float32)
            total = total + jnp.sum(v * v)
        return total

    def _dygraph_clip(self, params_grads):
        total = self._global_norm_sq(params_grads)
        global_norm = jnp.sqrt(total)
        scale = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-6), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            v = g.value
            out.append((p, Tensor((v.astype(jnp.float32) * scale).astype(v.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    params = [parameters] if isinstance(parameters, Tensor) else list(parameters)
    grads = [p.grad for p in params if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g.value)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g.value.astype(jnp.float32)) ** norm_type) for g in grads]
        )) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        if p.grad is not None:
            p.grad = Tensor((p.grad.value * scale).astype(p.grad.value.dtype))
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    params = [parameters] if isinstance(parameters, Tensor) else list(parameters)
    for p in params:
        if p.grad is not None:
            p.grad = Tensor(jnp.clip(p.grad.value, -clip_value, clip_value))
    return params
