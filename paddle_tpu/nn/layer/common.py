"""Common layers (python/paddle/nn/layer/common.py parity)."""
from __future__ import annotations

import math

from .. import functional as F
from ..initializer import Constant, Normal, Uniform, XavierUniform
from .layers import Layer

__all__ = [
    "Linear", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout", "Embedding",
    "Flatten", "Unflatten", "Identity", "Upsample", "UpsamplingBilinear2D",
    "UpsamplingNearest2D", "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D", "Bilinear",
    "CosineSimilarity", "PairwiseDistance", "PixelShuffle", "PixelUnshuffle",
    "ChannelShuffle", "Fold", "Unfold", "LinearCompress",
]


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b with W:[in,out] (reference nn/layer/common.py Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


LinearCompress = Linear  # quant-aware variant keeps the same math here


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, x):
        return F.dropout(x, self.p, self.axis, self.training, self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, self.training, self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, self.training, self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, self.training)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierUniform())
        if padding_idx is not None:
            import jax.numpy as jnp

            pid = padding_idx if padding_idx >= 0 else num_embeddings + padding_idx
            self.weight._value = self.weight._value.at[pid].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ...ops.manipulation import flatten

        return flatten(x, self.start_axis, self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        from ...ops.manipulation import unflatten

        return unflatten(x, self.axis, self.shape)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode, self.data_format = align_mode, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingNearest2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor, self.data_format = size, scale_factor, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, "nearest",
                             data_format=self.data_format)


class UpsamplingBilinear2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor, self.data_format = size, scale_factor, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, "bilinear",
                             align_corners=True, data_format=self.data_format)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self._pad = padding
        self._mode = mode
        self._value = value
        self._data_format = data_format

    def forward(self, x):
        return F.pad(x, self._pad, self._mode, self._value, self._data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        if isinstance(padding, int):
            padding = [padding, padding]
        super().__init__(padding, mode, value,
                         "NCW" if data_format in ("NCL", "NCW") else "NWC")


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        if isinstance(padding, int):
            padding = [padding] * 4
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        if isinstance(padding, int):
            padding = [padding] * 6
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        k = 1.0 / math.sqrt(in1_features)
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr,
            default_initializer=Uniform(-k, k))
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True,
            default_initializer=Uniform(-k, k))

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self._axis, self._eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self._axis, self._eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._factor, self._data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self._factor, self._data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._factor, self._data_format = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self._factor, self._data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self._groups, self._data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self._groups, self._data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, *self.args)
