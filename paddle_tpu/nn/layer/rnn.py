"""Recurrent layers (python/paddle/nn/layer/rnn.py parity).

The time loop is a single ``lax.scan`` — compiled once, no per-step Python
dispatch (the reference runs cudnn RNN kernels; scan+matmul is the XLA/TPU
equivalent and lets the MXU batch the gate matmuls).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.tensor import Tensor
from ...ops._helpers import unwrap
from ..initializer import Uniform
from .container import LayerList
from .layers import Layer

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell",
           "RNN", "BiRNN", "SimpleRNN", "LSTM", "GRU"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        from ...ops.creation import full

        state_shape = self.state_shape
        if isinstance(state_shape, tuple):
            return tuple(full([batch] + list(s), init_value) for s in state_shape)
        return full([batch] + list(state_shape), init_value)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], weight_ih_attr, default_initializer=Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], weight_hh_attr, default_initializer=Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            [hidden_size], bias_ih_attr, is_bias=True, default_initializer=Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            [hidden_size], bias_hh_attr, is_bias=True, default_initializer=Uniform(-std, std))
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        self._act = jnp.tanh if activation == "tanh" else jax.nn.relu

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = self._act

        def f(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)

        h = apply_op(f, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh, op_name="simple_rnn_cell")
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], weight_ih_attr, default_initializer=Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], weight_hh_attr, default_initializer=Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], bias_ih_attr, is_bias=True, default_initializer=Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], bias_hh_attr, is_bias=True, default_initializer=Uniform(-std, std))
        self.input_size = input_size
        self.hidden_size = hidden_size

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h0, c0 = states

        def f(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f_, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f_ = jax.nn.sigmoid(f_)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c_new = f_ * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new

        h, c = apply_op(f, inputs, h0, c0, self.weight_ih, self.weight_hh,
                        self.bias_ih, self.bias_hh, op_name="lstm_cell")
        return h, (h, c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], weight_ih_attr, default_initializer=Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], weight_hh_attr, default_initializer=Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], bias_ih_attr, is_bias=True, default_initializer=Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], bias_hh_attr, is_bias=True, default_initializer=Uniform(-std, std))
        self.input_size = input_size
        self.hidden_size = hidden_size

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def f(x, h, wi, wh, bi, bh):
            xg = x @ wi.T + bi
            hg = h @ wh.T + bh
            xr, xz, xc = jnp.split(xg, 3, axis=-1)
            hr, hz, hc = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            c = jnp.tanh(xc + r * hc)
            return (1 - z) * c + z * h

        h = apply_op(f, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh, op_name="gru_cell")
        return h, h


def _cell_pure(cell):
    """Return (pure_step(params, x_t, state) -> (out, state), params) for scan."""
    if isinstance(cell, LSTMCell):
        params = (cell.weight_ih.value, cell.weight_hh.value,
                  cell.bias_ih.value, cell.bias_hh.value)

        def step(p, x, st):
            wi, wh, bi, bh = p
            h, c = st
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f_, g, o = jnp.split(gates, 4, axis=-1)
            c_new = jax.nn.sigmoid(f_) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return h_new, (h_new, c_new)

        return step, params
    if isinstance(cell, GRUCell):
        params = (cell.weight_ih.value, cell.weight_hh.value,
                  cell.bias_ih.value, cell.bias_hh.value)

        def step(p, x, st):
            wi, wh, bi, bh = p
            xg = x @ wi.T + bi
            hg = st @ wh.T + bh
            xr, xz, xc = jnp.split(xg, 3, axis=-1)
            hr, hz, hc = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            c = jnp.tanh(xc + r * hc)
            h = (1 - z) * c + z * st
            return h, h

        return step, params
    # SimpleRNNCell
    act = jnp.tanh if getattr(cell, "activation", "tanh") == "tanh" else jax.nn.relu
    params = (cell.weight_ih.value, cell.weight_hh.value,
              cell.bias_ih.value, cell.bias_hh.value)

    def step(p, x, st):
        wi, wh, bi, bh = p
        h = act(x @ wi.T + bi + st @ wh.T + bh)
        return h, h

    return step, params


class RNN(Layer):
    """Wraps a cell into a scan over the time axis."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        step, _ = _cell_pure(self.cell)
        is_lstm = isinstance(self.cell, LSTMCell)
        time_major = self.time_major
        reverse = self.is_reverse
        seq_len = unwrap(sequence_length) if sequence_length is not None else None

        if initial_states is None:
            batch_ax = 1 if time_major else 0
            from ...ops.creation import zeros

            b = inputs.shape[batch_ax]
            hs = self.cell.hidden_size
            if is_lstm:
                initial_states = (zeros([b, hs], dtype=str(inputs.dtype)),
                                  zeros([b, hs], dtype=str(inputs.dtype)))
            else:
                initial_states = zeros([b, hs], dtype=str(inputs.dtype))

        cell_params = [self.cell.weight_ih, self.cell.weight_hh,
                       self.cell.bias_ih, self.cell.bias_hh]

        def run(x, wi, wh, bi, bh, *states):
            p = (wi, wh, bi, bh)
            st = (states[0], states[1]) if is_lstm else states[0]
            xs = x if time_major else jnp.swapaxes(x, 0, 1)  # [T, B, I]
            T = xs.shape[0]
            if reverse:
                xs = jnp.flip(xs, 0)

            def scan_fn(carry, inp):
                if seq_len is not None:
                    x_t, t = inp
                else:
                    x_t = inp
                out, new_st = step(p, x_t, carry)
                if seq_len is not None:
                    # freeze state past each sequence's length
                    tt = (T - 1 - t) if reverse else t
                    mask = (tt < seq_len)[:, None]
                    if is_lstm:
                        new_st = (jnp.where(mask, new_st[0], carry[0]),
                                  jnp.where(mask, new_st[1], carry[1]))
                    else:
                        new_st = jnp.where(mask, new_st, carry)
                    out = jnp.where(mask, out, 0.0)
                return new_st, out

            xs_in = (xs, jnp.arange(T)) if seq_len is not None else xs
            final, outs = jax.lax.scan(scan_fn, st, xs_in)
            if reverse:
                outs = jnp.flip(outs, 0)
            if not time_major:
                outs = jnp.swapaxes(outs, 0, 1)
            if is_lstm:
                return outs, final[0], final[1]
            return outs, final

        if is_lstm:
            outs, h, c = apply_op(run, inputs, *cell_params, *initial_states,
                                  op_name="rnn_scan")
            return outs, (h, c)
        outs, h = apply_op(run, inputs, *cell_params, initial_states,
                           op_name="rnn_scan")
        return outs, h


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            states_fw = states_bw = None
        else:
            states_fw, states_bw = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, states_fw, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw, sequence_length)
        from ...ops.manipulation import concat

        outputs = concat([out_fw, out_bw], axis=-1)
        return outputs, (st_fw, st_bw)


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        bidirect = 2 if direction in ("bidirect", "bidirectional") else 1
        self.num_directions = bidirect

        def make_cell(in_sz):
            kw = dict(weight_ih_attr=weight_ih_attr, weight_hh_attr=weight_hh_attr,
                      bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)
            if mode == "LSTM":
                return LSTMCell(in_sz, hidden_size, **kw)
            if mode == "GRU":
                return GRUCell(in_sz, hidden_size, **kw)
            act = "tanh" if mode == "RNN_TANH" else "relu"
            return SimpleRNNCell(in_sz, hidden_size, activation=act, **kw)

        self.rnns = LayerList()
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 else hidden_size * bidirect
            if bidirect == 2:
                self.rnns.append(BiRNN(make_cell(in_sz), make_cell(in_sz), time_major))
            else:
                self.rnns.append(RNN(make_cell(in_sz), time_major=time_major))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        is_lstm = self.mode == "LSTM"
        D = self.num_directions
        L = self.num_layers
        states_per_layer = [None] * L
        if initial_states is not None:
            # paddle shape: [L*D, B, H] (h) and same for c
            from ...ops.manipulation import split

            if is_lstm:
                h0, c0 = initial_states
                hs = split(h0, L * D, axis=0)
                cs = split(c0, L * D, axis=0)
                for l in range(L):
                    if D == 2:
                        states_per_layer[l] = (
                            ((hs[2 * l][0], cs[2 * l][0])),
                            ((hs[2 * l + 1][0], cs[2 * l + 1][0])))
                    else:
                        states_per_layer[l] = (hs[l][0], cs[l][0])
            else:
                hs = split(initial_states, L * D, axis=0)
                for l in range(L):
                    if D == 2:
                        states_per_layer[l] = (hs[2 * l][0], hs[2 * l + 1][0])
                    else:
                        states_per_layer[l] = hs[l][0]

        out = inputs
        finals = []
        for l, rnn in enumerate(self.rnns):
            out, st = rnn(out, states_per_layer[l], sequence_length)
            finals.append(st)
            if self.dropout > 0 and l < L - 1:
                from .. import functional as F

                out = F.dropout(out, self.dropout, training=self.training)

        from ...ops.manipulation import stack

        if is_lstm:
            if D == 2:
                hh = [s[d][0] for s in finals for d in range(2)]
                cc = [s[d][1] for s in finals for d in range(2)]
            else:
                hh = [s[0] for s in finals]
                cc = [s[1] for s in finals]
            return out, (stack(hh, axis=0), stack(cc, axis=0))
        if D == 2:
            hh = [s[d] for s in finals for d in range(2)]
        else:
            hh = finals
        return out, stack(hh, axis=0)


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout, weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, proj_size=0, name=None):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr)
