"""Normalization layers (python/paddle/nn/layer/norm.py parity).

BatchNorm running stats live as non-trainable buffers updated eagerly in
training mode — inside a jitted train step, use the functional form with
explicit state threading (paddle_tpu.jit handles the capture).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import functional as F
from ...core.tensor import Tensor
from ..initializer import Constant
from .layers import Layer

__all__ = [
    "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "SyncBatchNorm",
    "LayerNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D",
    "InstanceNorm3D", "LocalResponseNorm", "SpectralNorm", "RMSNorm",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}"


class BatchNorm(_BatchNormBase):
    """Legacy paddle.nn.BatchNorm (act fused)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout, use_global_stats)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format in ("NCL", "NC") else "NLC",
                         use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm. Inside pjit/shard_map the batch axis is
    sharded over 'dp'; stats sync is an axis-mean (lax.pmean) when tracing
    under a mesh context (reference: nn/layer/norm.py SyncBatchNorm over
    ProcessGroupNCCL)."""

    def forward(self, x):
        from ...distributed import env as dist_env

        axis = dist_env.current_sync_axis()
        if axis is None or not self.training:
            return super().forward(x)
        import jax

        def f(v, w, b):
            ch_ax = 1 if self._data_format.startswith("NC") else v.ndim - 1
            axes = tuple(i for i in range(v.ndim) if i != ch_ax)
            m = jax.lax.pmean(jnp.mean(v, axis=axes), axis)
            m2 = jax.lax.pmean(jnp.mean(v * v, axis=axes), axis)
            var = m2 - m * m
            shape = [1] * v.ndim
            shape[ch_ax] = v.shape[ch_ax]
            out = (v - m.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + self._epsilon)
            return out * w.reshape(shape) + b.reshape(shape)

        from ...core.autograd import apply_op

        return apply_op(f, x, self.weight, self.bias, op_name="sync_batch_norm")

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon, data_format=layer._data_format)
            out.weight.set_value(layer.weight)
            out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """RMS norm (paddle.incubate.nn.FusedRMSNorm analog; Llama-family default)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr, default_initializer=Constant(1.0))

    def forward(self, x):
        from ...core.autograd import apply_op
        from ...framework.flags import get_flags

        from ...ops.pallas import _on_tpu

        # pallas only on real TPU here: off-TPU the model path must stay
        # plain XLA so multi-device (GSPMD) dryruns don't trace interpret-
        # mode pallas_call inside pjit. The kernel itself is still covered
        # off-TPU through the incubate functional surface (interpret mode).
        if (_on_tpu()
                and get_flags("FLAGS_use_pallas_kernels")["FLAGS_use_pallas_kernels"]):
            from ...ops import pallas_kernels as pk

            return apply_op(
                lambda v, w: pk.rms_norm(v, w, eps=self._epsilon),
                x, self.weight, op_name="rms_norm")
        import jax

        def f(v, w):
            var = jnp.mean((v.astype(jnp.float32)) ** 2, axis=-1, keepdims=True)
            out = v * jax.lax.rsqrt(var + self._epsilon).astype(v.dtype)
            return out * w

        return apply_op(f, x, self.weight, op_name="rms_norm")


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.weight = None if weight_attr is False else self.create_parameter(
                [num_features], default_initializer=Constant(1.0))
            self.bias = None if bias_attr is False else self.create_parameter(
                [num_features], is_bias=True)
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr, default_initializer=Constant(1.0))
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """Power-iteration spectral normalization of a weight tensor."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from ..initializer import Normal

        self.weight_u = self.create_parameter(
            [h], default_initializer=Normal(0, 1.0))
        self.weight_v = self.create_parameter(
            [w], default_initializer=Normal(0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...core.autograd import apply_op
        import jax

        u0 = self.weight_u.value
        v0 = self.weight_v.value
        dim = self._dim
        iters = self._power_iters
        eps = self._eps

        def f(w):
            wm = jnp.moveaxis(w, dim, 0)
            mat = wm.reshape(wm.shape[0], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            u = jax.lax.stop_gradient(u)
            v = jax.lax.stop_gradient(v)
            sigma = u @ mat @ v
            return w / sigma

        out = apply_op(f, weight, op_name="spectral_norm")
        return out
