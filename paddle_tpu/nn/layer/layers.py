"""Layer — the module system.

TPU-native analog of the reference's ``paddle.nn.Layer``
(python/paddle/nn/layer/layers.py): named parameter/buffer/sublayer registry,
state_dict round-trip, train/eval mode, forward hooks. Parameters hold jax
arrays; a Layer is also viewable as a pytree of arrays (``raw_state``)
so whole models drop into jitted/pjit-ed functions without translation.
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dtype as dtypes
from ...core.tensor import Tensor
from ..initializer import Constant, Initializer, XavierUniform, get_global_initializer
from ..param_attr import ParamAttr
from ..parameter import Parameter

__all__ = ["Layer"]

_layer_name_counters: Dict[str, int] = collections.defaultdict(int)


class HookRemoveHelper:
    def __init__(self, hooks: dict, idx: int):
        self._hooks = hooks
        self._idx = idx

    def remove(self):
        self._hooks.pop(self._idx, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        # dtype=None → paddle.get_default_dtype() (paddle parity: layers honor
        # set_default_dtype at construction time)
        cls = name_scope or self.__class__.__name__.lower()
        _layer_name_counters[cls] += 1
        object.__setattr__(self, "_full_name", f"{cls}_{_layer_name_counters[cls] - 1}")
        object.__setattr__(self, "_dtype", dtypes.convert_dtype(dtype) or dtypes.get_default_dtype())
        object.__setattr__(self, "training", True)
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names_set", set())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_forward_pre_hooks", collections.OrderedDict())
        object.__setattr__(self, "_forward_post_hooks", collections.OrderedDict())
        object.__setattr__(self, "_hook_id", 0)
        object.__setattr__(self, "_casted_by_pure_fp16", False)

    # -- forward -----------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- registration ------------------------------------------------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias: bool = False,
        default_initializer: Optional[Initializer] = None,
    ) -> Optional[Parameter]:
        """Layer.create_parameter parity (nn/layer/layers.py)."""
        attr = ParamAttr._to_attr(attr)
        if attr is None:  # attr=False → no parameter (e.g. bias_attr=False)
            return None
        dtype = dtypes.convert_dtype(dtype) or self._dtype
        init = attr.initializer
        if init is None:
            gw, gb = get_global_initializer()
            init = (gb if is_bias else gw) or default_initializer
        if init is None:
            init = Constant(0.0) if is_bias else XavierUniform()
        value = init(shape, dtype)
        return Parameter(
            value,
            trainable=attr.trainable,
            name=attr.name,
            learning_rate=attr.learning_rate,
            regularizer=attr.regularizer,
            need_clip=attr.need_clip,
            do_model_average=attr.do_model_average,
        )

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter or None")
        self._parameters[name] = parameter
        if name in self.__dict__:
            del self.__dict__[name]
        return parameter

    def register_buffer(self, name: str, tensor, persistable: bool = True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(jnp.asarray(tensor), stop_gradient=True)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names_set.add(name)
        else:
            self._non_persistable_buffer_names_set.discard(name)
        return tensor

    def add_sublayer(self, name: str, sublayer: "Layer"):
        if sublayer is not None and not isinstance(sublayer, Layer):
            raise TypeError("add_sublayer expects a Layer")
        self._sub_layers[name] = sublayer
        return sublayer

    # -- attribute routing -------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__() before assigning parameters")
            self._buffers.pop(name, None)
            self._sub_layers.pop(name, None)
            params[name] = value
            self.__dict__.pop(name, None)
            return
        layers = self.__dict__.get("_sub_layers")
        if isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__() before assigning sublayers")
            params.pop(name, None)
            self._buffers.pop(name, None)
            layers[name] = value
            self.__dict__.pop(name, None)
            return
        # assigning over an existing registered slot
        if params is not None and name in params:
            if value is None:
                params[name] = None
                return
            if isinstance(value, Tensor):
                params[name].set_value(value)
                return
            del params[name]
        buffers = self.__dict__.get("_buffers")
        if buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
                return
            del buffers[name]
        if layers is not None and name in layers and not isinstance(value, Layer):
            del layers[name]
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{self.__class__.__name__}' object has no attribute '{name}'"
        )

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = (
            list(self._parameters) + list(self._buffers) + list(self._sub_layers)
        )
        return sorted(set(list(super().__dir__()) + extra))

    # -- traversal ---------------------------------------------------------
    def full_name(self) -> str:
        return self._full_name

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False,
                        layers_set=None) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, l in self.named_children():
            if l is None:
                continue
            sub_prefix = prefix + ("." if prefix else "") + name
            yield from l.named_sublayers(
                prefix=sub_prefix, include_self=True, layers_set=layers_set
            )

    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        params_set = set()
        layers = (
            self.named_sublayers(prefix=prefix, include_self=True)
            if include_sublayers
            else [(prefix, self)]
        )
        for layer_prefix, layer in layers:
            for name, p in layer._parameters.items():
                if p is None or id(p) in params_set:
                    continue
                params_set.add(id(p))
                yield layer_prefix + ("." if layer_prefix else "") + name, p

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True
                      ) -> Iterator[Tuple[str, Tensor]]:
        buffers_set = set()
        layers = (
            self.named_sublayers(prefix=prefix, include_self=True)
            if include_sublayers
            else [(prefix, self)]
        )
        for layer_prefix, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or id(b) in buffers_set:
                    continue
                buffers_set.add(id(b))
                yield layer_prefix + ("." if layer_prefix else "") + name, b

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- modes -------------------------------------------------------------
    def train(self) -> "Layer":
        object.__setattr__(self, "training", True)
        for l in self.children():
            l.train()
        return self

    def eval(self) -> "Layer":
        object.__setattr__(self, "training", False)
        for l in self.children():
            l.eval()
        return self

    # -- state dict --------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True
                   ) -> Dict[str, Tensor]:
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip("."),
                                             include_sublayers=include_sublayers):
            dest[name] = p
        # persistable buffers only
        layers = (
            self.named_sublayers(prefix=structured_name_prefix.rstrip("."), include_self=True)
            if include_sublayers
            else [(structured_name_prefix.rstrip("."), self)]
        )
        seen = set()
        for layer_prefix, layer in layers:
            for name, b in layer._buffers.items():
                if (b is None or id(b) in seen
                        or name in layer._non_persistable_buffer_names_set):
                    continue
                seen.add(id(b))
                dest[layer_prefix + ("." if layer_prefix else "") + name] = b
        return dest

    def set_state_dict(self, state_dict: Dict[str, Any], use_structured_name: bool = True):
        """Returns (missing_keys, unexpected_keys) like the reference."""
        own = self.state_dict()
        missing, matched = [], set()
        for key, target in own.items():
            if key in state_dict:
                v = state_dict[key]
                if isinstance(v, Tensor):
                    v = v.value
                v = jnp.asarray(np.asarray(v))
                if tuple(v.shape) != tuple(target.shape):
                    raise ValueError(
                        f"shape mismatch for {key}: receives {tuple(v.shape)}, "
                        f"expects {tuple(target.shape)}"
                    )
                target.set_value(v.astype(target.dtype))
                matched.add(key)
            else:
                missing.append(key)
        unexpected = [k for k in state_dict if k not in own]
        return missing, unexpected

    # aliases kept by the reference
    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- dtype/device conversion -------------------------------------------
    def _transform(self, fn):
        for _, p in self.named_parameters():
            p._value = fn(p._value)
        for _, b in self.named_buffers():
            b._value = fn(b._value)
        return self

    def to(self, device=None, dtype=None, blocking=None) -> "Layer":
        d = dtypes.convert_dtype(dtype) if dtype is not None else None

        def fn(v):
            if d is not None and jnp.issubdtype(v.dtype, jnp.floating):
                v = v.astype(d)
            if device is not None:
                from ...core.place import Place
                from ...core.tensor import _parse_place

                place = device if isinstance(device, Place) else _parse_place(str(device))
                v = jax.device_put(v, place.jax_device())
            return v

        if d is not None:
            object.__setattr__(self, "_dtype", d)
        return self._transform(fn)

    def astype(self, dtype) -> "Layer":
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def float16(self):
        return self.half()

    # -- misc --------------------------------------------------------------
    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self.named_children():
            mod_str = repr(l)
            mod_str = _addindent(mod_str, 2)
            lines.append(f"({name}): {mod_str}")
        main = self.__class__.__name__ + "("
        if extra:
            main += extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"

    # -- pytree view (TPU-native: drop a whole model into jit/pjit) --------
    def raw_state(self) -> Dict[str, Any]:
        """{name: jax array} for params + persistable buffers."""
        return {k: v._value for k, v in self.state_dict().items()}

    def load_raw_state(self, raw: Dict[str, Any]):
        sd = self.state_dict()
        for k, v in raw.items():
            if k in sd:
                sd[k]._value = jnp.asarray(v, sd[k].dtype)
        return self


def _addindent(s: str, num_spaces: int) -> str:
    lines = s.split("\n")
    if len(lines) == 1:
        return s
    first = lines.pop(0)
    rest = "\n".join((" " * num_spaces) + line for line in lines)
    return first + "\n" + rest
