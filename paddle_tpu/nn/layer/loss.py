"""Loss layers (python/paddle/nn/layer/loss.py parity)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = [
    "CrossEntropyLoss", "NLLLoss", "BCELoss", "BCEWithLogitsLoss", "MSELoss",
    "L1Loss", "SmoothL1Loss", "KLDivLoss", "MarginRankingLoss",
    "HingeEmbeddingLoss", "CosineEmbeddingLoss", "CTCLoss",
    "TripletMarginLoss", "TripletMarginWithDistanceLoss",
    "MultiLabelSoftMarginLoss", "SoftMarginLoss", "PoissonNLLLoss",
    "GaussianNLLLoss",
]


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.a = dict(ignore_index=ignore_index, reduction=reduction,
                      soft_label=soft_label, axis=axis, use_softmax=use_softmax,
                      label_smoothing=label_smoothing)

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self.weight, **self.a)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self.weight, self.ignore_index, self.reduction = weight, ignore_index, reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index, self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight, self.reduction, self.pos_weight = weight, reduction, pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction, self.log_target = reduction, log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin, self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin, self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.a = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, *self.a)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight, self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.a = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, *self.a)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, self.full,
                                   self.epsilon, self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid classifier head (reference nn/layer/loss.py
    HSigmoidLoss): owns the (num_classes-1, feature) internal-node weights."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if num_classes < 2:
            raise ValueError("num_classes must be >= 2")
        self.num_classes = num_classes
        self.is_custom = is_custom
        self.weight = self.create_parameter(
            shape=[num_classes - 1, feature_size], attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_classes - 1], attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        from ..functional.sequence_loss import hsigmoid_loss

        return hsigmoid_loss(input, label, self.num_classes, self.weight,
                             self.bias, path_table=path_table,
                             path_code=path_code)


class MultiMarginLoss(Layer):
    """reference nn/layer/loss.py MultiMarginLoss."""

    def __init__(self, p: int = 1, margin: float = 1.0, weight=None,
                 reduction: str = "mean", name=None):
        super().__init__()
        self.p = p
        self.margin = margin
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        from ..functional.sequence_loss import multi_margin_loss

        return multi_margin_loss(input, label, p=self.p, margin=self.margin,
                                 weight=self.weight,
                                 reduction=self.reduction)


class RNNTLoss(Layer):
    """reference nn/layer/loss.py RNNTLoss."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        from ..functional.sequence_loss import rnnt_loss

        return rnnt_loss(input, label, input_lengths, label_lengths,
                         blank=self.blank,
                         fastemit_lambda=self.fastemit_lambda,
                         reduction=self.reduction)


__all__ += ["HSigmoidLoss", "MultiMarginLoss", "RNNTLoss"]
