"""functional_call — run a Layer's forward with substituted parameter values.

The bridge between the eager Layer API and jitted/pjit-ed training steps:
a Layer becomes a pure function of (params, buffers, inputs), so whole
models drop into ``jax.jit``/``jax.grad`` with donated, mesh-sharded param
pytrees. (The reference needs dy2static AST rewriting for this,
jit/dy2static/program_translator.py:305; under tracing it is just value
substitution.)
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, Optional

from ..core.tensor import Tensor

__all__ = ["functional_call", "substituted_state"]


@contextlib.contextmanager
def substituted_state(layer, params: Optional[Dict[str, Any]] = None,
                      buffers: Optional[Dict[str, Any]] = None):
    """Temporarily swap the raw values of `layer`'s named parameters/buffers.
    Values may be jax arrays or tracers; autograd nodes are detached for the
    scope so the substituted values are true leaves."""
    named_p = dict(layer.named_parameters())
    named_b = dict(layer.named_buffers())
    old_p = {k: (p._value, p._node) for k, p in named_p.items()}
    old_b = {k: b._value for k, b in named_b.items()}
    try:
        if params:
            unknown = set(params) - set(named_p)
            if unknown:
                raise KeyError(
                    f"params keys not found in layer.named_parameters(): "
                    f"{sorted(unknown)[:5]}{'...' if len(unknown) > 5 else ''}")
            for k, v in params.items():
                p = named_p[k]
                p._value = v._value if isinstance(v, Tensor) else v
                p._node = None
        if buffers:
            unknown = set(buffers) - set(named_b)
            if unknown:
                raise KeyError(
                    f"buffers keys not found in layer.named_buffers(): "
                    f"{sorted(unknown)[:5]}")
            for k, v in buffers.items():
                named_b[k]._value = v._value if isinstance(v, Tensor) else v
        yield layer
    finally:
        for k, p in named_p.items():
            p._value, p._node = old_p[k]
        for k, b in named_b.items():
            b._value = old_b[k]


def functional_call(layer, params: Optional[Dict[str, Any]], *args,
                    buffers: Optional[Dict[str, Any]] = None, **kwargs):
    """Run ``layer(*args, **kwargs)`` with parameter values taken from
    `params` (a dict keyed like ``named_parameters``). Returns raw jax values
    (Tensor outputs are unwrapped) so the caller composes with jax.grad."""
    import jax

    from ..core.autograd import no_grad

    # no_grad: suppress the eager per-op tape (jax.vjp) — differentiation is
    # the OUTER transform's job (jax.grad over this function). Nesting the
    # tape under jax.grad creates higher-order AD, which kernels with
    # custom_vjp (pallas flash attention) reject.
    with substituted_state(layer, params, buffers), no_grad():
        out = layer(*args, **kwargs)
    return jax.tree.map(
        lambda o: o._value if isinstance(o, Tensor) else o, out,
        is_leaf=lambda o: isinstance(o, Tensor))
