"""Parameter initializers.

TPU-native analog of the reference's fill/init ops
(python/paddle/nn/initializer/*.py): each initializer is a pure function of a
PRNG key + shape + dtype, evaluated once at parameter creation (there is no
lazy "init op" graph to run — jax arrays are values).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.random import default_generator

__all__ = [
    "Initializer",
    "Constant",
    "Normal",
    "TruncatedNormal",
    "Uniform",
    "XavierNormal",
    "XavierUniform",
    "KaimingNormal",
    "KaimingUniform",
    "Assign",
    "Dirac",
    "Orthogonal",
    "calculate_gain",
]


def calculate_gain(nonlinearity: str, param=None) -> float:
    """python/paddle/nn/initializer/initializer.py:calculate_gain parity."""
    recommended = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "conv1d_transpose": 1.0,
        "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0,
        "tanh": 5.0 / 3,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    if nonlinearity not in recommended:
        raise ValueError(f"unsupported nonlinearity: {nonlinearity}")
    return recommended[nonlinearity]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle stores [out_c, in_c, *spatial]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype=None, key=None):
        dtype = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        if key is None:
            key = default_generator.next_key()
        return self._generate(key, tuple(int(s) for s in shape), dtype)

    def _generate(self, key, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _generate(self, key, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _generate(self, key, shape, dtype):
        return self.mean + self.std * jax.random.normal(key, shape, dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def _generate(self, key, shape, dtype):
        # bounds are in units of std around mean (reference truncated_gaussian_random)
        lo = (self.a - 0.0)
        hi = (self.b - 0.0)
        return self.mean + self.std * jax.random.truncated_normal(key, lo, hi, shape, dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def _generate(self, key, shape, dtype):
        return jax.random.uniform(key, shape, dtype, self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, key, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(key, shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, key, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(key, shape, dtype, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, key, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(key, shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, key, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(key, shape, dtype, -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def _generate(self, key, shape, dtype):
        from ..core.tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = v.value
        arr = jnp.asarray(np.asarray(v), dtype)
        if tuple(arr.shape) != shape:
            arr = arr.reshape(shape)
        return arr


class Dirac(Initializer):
    """Identity-preserving conv kernel init (nn/initializer/dirac.py)."""

    def __init__(self, groups=1):
        self.groups = groups

    def _generate(self, key, shape, dtype):
        arr = np.zeros(shape, dtype=np.float32)
        out_c, in_c = shape[0], shape[1]
        out_per_group = out_c // self.groups
        mins = min(out_per_group, in_c)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for d in range(mins):
                idx = (g * out_per_group + d, d, *centers)
                arr[idx] = 1.0
        return jnp.asarray(arr, dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def _generate(self, key, shape, dtype):
        if len(shape) < 2:
            raise ValueError("Orthogonal init needs >=2 dims")
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        q = jax.random.orthogonal(key, max(rows, cols), dtype=jnp.float32)
        q = q[:rows, :cols]
        return (self.gain * q).reshape(shape).astype(dtype)


# paddle.nn.initializer.set_global_initializer support
_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def get_global_initializer():
    return _global_weight_init, _global_bias_init


class Bilinear(Initializer):
    """Bilinear-upsampling kernel initializer for transposed conv
    (reference nn/initializer/Bilinear: the classic FCN upsample filter).
    Weight layout [in_c, out_c/groups, kH, kW]; each spatial kernel gets
    the separable triangle filter."""

    def _generate(self, key, shape, dtype):
        if len(shape) != 4:
            raise ValueError("Bilinear expects a 4-D conv weight")
        kh, kw = shape[2], shape[3]

        def tri(k):
            f = (k + 1) // 2
            c = (2 * f - 1 - f % 2) / (2.0 * f)
            x = jnp.arange(k, dtype=jnp.float32)
            return 1.0 - jnp.abs(x / f - c)

        kern = tri(kh)[:, None] * tri(kw)[None, :]
        return jnp.broadcast_to(kern, shape).astype(dtype)


__all__.append("Bilinear")
