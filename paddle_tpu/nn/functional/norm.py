"""Normalization functionals.

Parity: python/paddle/nn/functional/norm.py (reference kernels:
phi/kernels/gpu/batch_norm_kernel.cu, layer_norm_kernel.cu). Plain jnp
reductions — XLA fuses mean/var/scale/shift into one pass on TPU.
batch_norm running-stat update happens eagerly on the module side.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.tensor import Tensor
from ...ops._helpers import unwrap

__all__ = ["batch_norm", "layer_norm", "instance_norm", "group_norm",
           "local_response_norm", "normalize"]


def normalize(x, p: float = 2, axis: int = 1, epsilon: float = 1e-12, name=None):
    def f(v):
        norm = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(norm, epsilon)

    return apply_op(f, x, op_name="normalize")


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training: bool = False, momentum: float = 0.9, epsilon: float = 1e-5,
               data_format: str = "NCHW", use_global_stats=None, name=None):
    """Functional batch norm. In training mode, updates running stats in-place
    on the provided Tensors (matching reference mutable-state semantics)."""
    channel_ax = 1 if data_format.startswith("NC") or data_format == "NC" else -1
    if use_global_stats is None:
        use_global_stats = not training

    def stats_axes(ndim):
        return tuple(i for i in range(ndim) if i != (channel_ax % ndim))

    if training and not use_global_stats:
        xv = unwrap(x)
        axes = stats_axes(xv.ndim)
        batch_mean = jnp.mean(xv, axis=axes)
        batch_var = jnp.var(xv, axis=axes)
        # running-stat update (reference: phi batch_norm updates with momentum)
        if isinstance(running_mean, Tensor):
            running_mean.set_value(momentum * running_mean.value + (1 - momentum) * batch_mean)
            running_var.set_value(momentum * running_var.value + (1 - momentum) * batch_var)

        def f(v, *wb):
            shape = [1] * v.ndim
            shape[channel_ax % v.ndim] = v.shape[channel_ax % v.ndim]
            m = jnp.mean(v, axis=axes).reshape(shape)
            var = jnp.var(v, axis=axes).reshape(shape)
            out = (v - m) * jax.lax.rsqrt(var + epsilon)
            if wb:
                out = out * wb[0].reshape(shape) + wb[1].reshape(shape)
            return out
    else:
        rm, rv = unwrap(running_mean), unwrap(running_var)

        def f(v, *wb):
            shape = [1] * v.ndim
            shape[channel_ax % v.ndim] = v.shape[channel_ax % v.ndim]
            out = (v - rm.reshape(shape)) * jax.lax.rsqrt(rv.reshape(shape) + epsilon)
            if wb:
                out = out * wb[0].reshape(shape) + wb[1].reshape(shape)
            return out

    if weight is not None:
        return apply_op(f, x, weight, bias, op_name="batch_norm")
    return apply_op(f, x, op_name="batch_norm")


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon: float = 1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(tuple(normalized_shape))

    def f(v, *wb):
        axes = tuple(range(v.ndim - n_axes, v.ndim))
        m = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - m) * jax.lax.rsqrt(var + epsilon)
        if wb:
            w = wb[0]
            out = out * w
            if len(wb) > 1 and wb[1] is not None:
                out = out + wb[1]
        return out

    if weight is not None and bias is not None:
        return apply_op(f, x, weight, bias, op_name="layer_norm")
    if weight is not None:
        return apply_op(f, x, weight, op_name="layer_norm")
    return apply_op(f, x, op_name="layer_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats: bool = True, momentum: float = 0.9,
                  eps: float = 1e-5, data_format: str = "NCHW", name=None):
    def f(v, *wb):
        axes = tuple(range(2, v.ndim))  # per-sample, per-channel spatial stats
        m = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - m) * jax.lax.rsqrt(var + eps)
        if wb:
            shape = [1, v.shape[1]] + [1] * (v.ndim - 2)
            out = out * wb[0].reshape(shape) + wb[1].reshape(shape)
        return out

    if weight is not None:
        return apply_op(f, x, weight, bias, op_name="instance_norm")
    return apply_op(f, x, op_name="instance_norm")


def group_norm(x, num_groups: int, epsilon: float = 1e-5, weight=None, bias=None,
               data_format: str = "NCHW", name=None):
    channel_last = not data_format.startswith("NC")

    def f(v, *wb):
        if channel_last:
            v_ = jnp.moveaxis(v, -1, 1)
        else:
            v_ = v
        n, c = v_.shape[0], v_.shape[1]
        rest = v_.shape[2:]
        g = v_.reshape(n, num_groups, c // num_groups, *rest)
        axes = tuple(range(2, g.ndim))
        m = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - m) * jax.lax.rsqrt(var + epsilon)).reshape(v_.shape)
        if wb:
            shape = [1, c] + [1] * (v_.ndim - 2)
            out = out * wb[0].reshape(shape) + wb[1].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    if weight is not None:
        return apply_op(f, x, weight, bias, op_name="group_norm")
    return apply_op(f, x, op_name="group_norm")


def local_response_norm(x, size: int, alpha: float = 1e-4, beta: float = 0.75,
                        k: float = 1.0, data_format: str = "NCHW", name=None):
    # paddle formula: out = x / (k + alpha/size * sum(x^2))^beta
    def f2(v):
        sq = v * v
        half = size // 2
        ch_ax = 1 if data_format.startswith("NC") else v.ndim - 1
        pad_width = [(0, 0)] * v.ndim
        pad_width[ch_ax] = (half, size - 1 - half)
        padded = jnp.pad(sq, pad_width)
        window = [1] * v.ndim
        window[ch_ax] = size
        s = jax.lax.reduce_window(
            padded, 0.0, jax.lax.add, window, [1] * v.ndim, "VALID"
        )
        return v / (k + (alpha / size) * s) ** beta

    return apply_op(f2, x, op_name="local_response_norm")
