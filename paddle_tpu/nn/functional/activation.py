"""Activation functionals (python/paddle/nn/functional/activation.py parity).

Each is a differentiable wrapper over jax.nn / jnp — XLA fuses these into
surrounding matmuls on TPU, so there are no hand-written activation kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops._helpers import diff_op, unwrap
from ...core.autograd import apply_op
from ...core.tensor import Tensor

__all__ = [
    "relu", "relu6", "relu_", "elu", "elu_", "selu", "celu", "gelu", "silu",
    "swish", "sigmoid", "hardsigmoid", "hardswish", "hardtanh", "hardshrink",
    "softshrink", "tanhshrink", "leaky_relu", "log_sigmoid", "log_softmax",
    "maxout", "prelu", "rrelu", "softmax", "softmax_", "softplus", "softsign",
    "mish", "tanh", "tanh_", "thresholded_relu", "glu", "gumbel_softmax",
]

relu = diff_op(jax.nn.relu, "relu")
relu_ = relu
sigmoid = diff_op(jax.nn.sigmoid, "sigmoid")
silu = diff_op(jax.nn.silu, "silu")
softsign = diff_op(jax.nn.soft_sign, "softsign")
tanh = diff_op(jnp.tanh, "tanh")
tanh_ = tanh
log_sigmoid = diff_op(jax.nn.log_sigmoid, "log_sigmoid")


def relu6(x, name=None):
    return apply_op(lambda v: jnp.clip(v, 0.0, 6.0), x, op_name="relu6")


def elu(x, alpha: float = 1.0, name=None):
    return apply_op(lambda v: jax.nn.elu(v, alpha), x, op_name="elu")


elu_ = elu


def selu(x, scale: float = 1.0507009873554805, alpha: float = 1.6732632423543772, name=None):
    return apply_op(
        lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)),
        x, op_name="selu",
    )


def celu(x, alpha: float = 1.0, name=None):
    return apply_op(lambda v: jax.nn.celu(v, alpha), x, op_name="celu")


def gelu(x, approximate: bool = False, name=None):
    return apply_op(
        lambda v: jax.nn.gelu(v, approximate=approximate), x, op_name="gelu"
    )


def swish(x, name=None):
    return silu(x)


def hardsigmoid(x, slope: float = 0.1666667, offset: float = 0.5, name=None):
    return apply_op(
        lambda v: jnp.clip(slope * v + offset, 0.0, 1.0), x, op_name="hardsigmoid"
    )


def hardswish(x, name=None):
    return apply_op(
        lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, x, op_name="hardswish"
    )


def hardtanh(x, min: float = -1.0, max: float = 1.0, name=None):
    return apply_op(lambda v: jnp.clip(v, min, max), x, op_name="hardtanh")


def hardshrink(x, threshold: float = 0.5, name=None):
    return apply_op(
        lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), x, op_name="hardshrink"
    )


def softshrink(x, threshold: float = 0.5, name=None):
    return apply_op(
        lambda v: jnp.where(v > threshold, v - threshold,
                            jnp.where(v < -threshold, v + threshold, 0.0)),
        x, op_name="softshrink",
    )


def tanhshrink(x, name=None):
    return apply_op(lambda v: v - jnp.tanh(v), x, op_name="tanhshrink")


def leaky_relu(x, negative_slope: float = 0.01, name=None):
    return apply_op(
        lambda v: jax.nn.leaky_relu(v, negative_slope), x, op_name="leaky_relu"
    )


def log_softmax(x, axis: int = -1, dtype=None, name=None):
    def f(v):
        if dtype is not None:
            from ...core.dtype import convert_dtype

            v = v.astype(convert_dtype(dtype))
        return jax.nn.log_softmax(v, axis=axis)

    return apply_op(f, x, op_name="log_softmax")


def softmax(x, axis: int = -1, dtype=None, name=None):
    def f(v):
        if dtype is not None:
            from ...core.dtype import convert_dtype

            v = v.astype(convert_dtype(dtype))
        return jax.nn.softmax(v, axis=axis)

    return apply_op(f, x, op_name="softmax")


softmax_ = softmax


def softplus(x, beta: float = 1.0, threshold: float = 20.0, name=None):
    return apply_op(
        lambda v: jnp.where(
            beta * v > threshold, v, (1.0 / beta) * jnp.log1p(jnp.exp(beta * v))
        ),
        x, op_name="softplus",
    )


def mish(x, name=None):
    return apply_op(lambda v: v * jnp.tanh(jax.nn.softplus(v)), x, op_name="mish")


def thresholded_relu(x, threshold: float = 1.0, name=None):
    return apply_op(
        lambda v: jnp.where(v > threshold, v, 0.0), x, op_name="thresholded_relu"
    )


def maxout(x, groups: int, axis: int = 1, name=None):
    def f(v):
        ax = axis if axis >= 0 else v.ndim + axis
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (groups, c // groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(new_shape), axis=ax)

    return apply_op(f, x, op_name="maxout")


def prelu(x, weight, data_format: str = "NCHW", name=None):
    def f(v, w):
        if w.size == 1:
            return jnp.where(v > 0, v, w.reshape(()) * v)
        ax = 1 if data_format in ("NCHW", "NCL", "NCDHW") else v.ndim - 1
        shape = [1] * v.ndim
        shape[ax] = w.size
        return jnp.where(v > 0, v, w.reshape(shape) * v)

    return apply_op(f, x, weight, op_name="prelu")


def rrelu(x, lower: float = 0.125, upper: float = 0.3333333, training: bool = False, name=None):
    if training:
        from ...core.random import default_generator

        k = default_generator.next_key()

        def f(v):
            a = jax.random.uniform(k, v.shape, v.dtype, lower, upper)
            return jnp.where(v >= 0, v, a * v)

        return apply_op(f, x, op_name="rrelu")
    mid = (lower + upper) / 2.0
    return leaky_relu(x, mid)


def glu(x, axis: int = -1, name=None):
    return apply_op(lambda v: jax.nn.glu(v, axis=axis), x, op_name="glu")


def gumbel_softmax(x, temperature: float = 1.0, hard: bool = False, axis: int = -1, name=None):
    from ...core.random import default_generator

    k = default_generator.next_key()

    def f(v):
        g = -jnp.log(-jnp.log(jax.random.uniform(k, v.shape, v.dtype, 1e-20, 1.0)))
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            onehot = jax.nn.one_hot(
                jnp.argmax(y, axis=axis), y.shape[axis], axis=axis, dtype=y.dtype
            )
            y = jax.lax.stop_gradient(onehot - y) + y
        return y

    return apply_op(f, x, op_name="gumbel_softmax")
