"""Sequence/classification loss tail (reference: python/paddle/nn/
functional/loss.py — hsigmoid_loss, rnnt_loss, multi_margin_loss,
margin_cross_entropy; python/paddle/nn/decode.py gather_tree).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.tensor import Tensor

__all__ = ["gather_tree", "hsigmoid_loss", "rnnt_loss",
           "multi_margin_loss", "margin_cross_entropy"]


def gather_tree(ids, parents):
    """Backtrace beam-search ids along parent pointers (reference
    nn/decode.py gather_tree / phi gather_tree kernel). ids/parents:
    [max_time, batch, beam]. One reverse lax.scan."""

    def f(idv, pv):
        T = idv.shape[0]

        def step(next_beam, t):
            # next_beam: [batch, beam] — which beam each output slot
            # follows at time t+1
            ids_t = jnp.take_along_axis(idv[t], next_beam, axis=1)
            par_t = jnp.take_along_axis(pv[t], next_beam, axis=1)
            return par_t, ids_t

        init = jnp.broadcast_to(jnp.arange(idv.shape[2])[None, :],
                                idv.shape[1:]).astype(pv.dtype)
        _, out = jax.lax.scan(step, init, jnp.arange(T), reverse=True)
        return out

    return apply_op(f, ids, parents, op_name="gather_tree")


def _simple_code(labels, num_classes, max_len):
    """Paddle SimpleCode (hsigmoid default complete-binary-tree coding):
    for class c, walk m = c + num_classes from the MSB: node ids
    (m >> k) - 1, branch bits (m >> (k-1)) & 1."""
    m = labels + num_classes
    nbits = jnp.floor(jnp.log2(m.astype(jnp.float32))).astype(jnp.int32)
    j = jnp.arange(max_len)
    shift = nbits[:, None] - j[None, :]
    valid = shift >= 1
    node = jnp.where(valid, (m[:, None] >> jnp.maximum(shift, 1)) - 1, 0)
    bit = jnp.where(valid,
                    (m[:, None] >> jnp.maximum(shift - 1, 0)) & 1, 0)
    return node, bit.astype(jnp.float32), valid


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference nn/functional/loss.py
    hsigmoid_loss / phi hsigmoid_loss kernel). Default coding is the
    complete-binary-tree SimpleCode; custom trees pass path_table (node
    ids, [N, L]) and path_code (branch bits, [N, L], -1 padded)."""
    max_len = int(math.ceil(math.log2(max(num_classes, 2))))

    def f(x, lbl, w, *rest):
        rest = list(rest)
        b = rest.pop(0) if bias is not None else None
        if path_table is not None:
            pt = rest.pop(0).astype(jnp.int32)
            pc = rest.pop(0).astype(jnp.float32)
            valid = pc >= 0
            pc = jnp.maximum(pc, 0.0)
        else:
            pt, pc, valid = _simple_code(lbl.reshape(-1).astype(jnp.int32),
                                         num_classes, max_len)
        # logits along each sample's path: [N, L]
        wp = w[pt]                               # [N, L, D]
        logit = jnp.einsum("nld,nd->nl", wp, x)
        if b is not None:
            logit = logit + b.reshape(-1)[pt]
        # bit==1 -> right branch: loss = softplus(logit) - bit*logit
        # (= -log sigmoid(±logit) with sign from the bit)
        loss = jax.nn.softplus(logit) - pc * logit
        loss = jnp.where(valid, loss, 0.0).sum(-1)
        return loss.reshape(-1, 1)

    args = [input, label, weight]
    if bias is not None:
        args.append(bias)
    if path_table is not None:
        args += [path_table, path_code]
    return apply_op(f, *args, op_name="hsigmoid_loss")


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-Transducer loss (reference nn/functional/loss.py rnnt_loss,
    warprnnt binding; Graves 2012). input: [B, T, U+1, V] log-probable
    logits (log_softmax applied here); label: [B, U].

    TPU-native: the alpha recursion runs as a lax.scan over T with an
    inner scan over U — log-space throughout, static shapes, masked tails.
    """

    def _nll(blank_lp, y_lp, ilen, llen):
        B, T, U1 = blank_lp.shape
        neg = -1e30

        def t_step(alpha_prev, t):
            # emit (horizontal, from t-1 same u) term
            from_left = jnp.where(t == 0, jnp.where(
                jnp.arange(U1)[None, :] == 0, 0.0, neg),
                alpha_prev + blank_lp[:, jnp.maximum(t - 1, 0)])

            # vertical recursion within this t: alpha[t,u] = logsumexp(
            #   from_left[u], alpha[t,u-1] + y(t, u-1))
            def u_step(carry, u):
                prev_u = carry
                cur = jnp.where(
                    u == 0, from_left[:, 0],
                    jnp.logaddexp(from_left[:, u],
                                  prev_u + y_lp[:, t, jnp.maximum(u - 1,
                                                                  0)]))
                return cur, cur

            _, cols = jax.lax.scan(u_step, jnp.full((B,), neg),
                                   jnp.arange(U1))
            alpha_t = jnp.swapaxes(cols, 0, 1)             # [B, U+1]
            return alpha_t, alpha_t

        alpha0 = jnp.full((B, U1), neg)
        _, alphas = jax.lax.scan(t_step, alpha0, jnp.arange(T))
        alphas = jnp.swapaxes(alphas, 0, 1)                # [B, T, U+1]
        t_last = (ilen - 1).astype(jnp.int32)
        u_last = llen.astype(jnp.int32)
        a_end = alphas[jnp.arange(B), t_last, u_last]
        final_blank = blank_lp[jnp.arange(B), t_last, u_last]
        return -(a_end + final_blank)

    def f(lg, lb, ilen, llen):
        logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
        U = logp.shape[2] - 1
        blank_lp = logp[..., blank]                        # [B, T, U+1]
        lb_i = lb.astype(jnp.int32)
        y_lp = jnp.take_along_axis(
            logp[:, :, :U, :], lb_i[:, None, :, None], axis=-1)[..., 0]
        nll = _nll(blank_lp, y_lp, ilen, llen)
        if fastemit_lambda > 0.0:
            # FastEmit (Yu et al. 2021): scale the EMISSION branch of the
            # gradient by (1 + lambda). Realized as an extra loss term
            # whose gradient flows only through the label log-probs (blank
            # contributions stop-gradiented) — grad = grad_blank +
            # (1+lambda) grad_emit, value shifted by lambda*L (constant
            # offset, same optimum).
            nll_emit = _nll(jax.lax.stop_gradient(blank_lp), y_lp,
                            ilen, llen)
            # zero-valued term: gradients only (loss VALUE matches the
            # plain transducer NLL exactly)
            nll = nll + fastemit_lambda * (
                nll_emit - jax.lax.stop_gradient(nll_emit))
        if reduction == "mean":
            return jnp.mean(nll)
        if reduction == "sum":
            return jnp.sum(nll)
        return nll

    return apply_op(f, input, label, input_lengths, label_lengths,
                    op_name="rnnt_loss")


def multi_margin_loss(input, label, p: int = 1, margin: float = 1.0,
                      weight=None, reduction: str = "mean", name=None):
    """Multi-class hinge loss (reference multi_margin_loss)."""

    def f(x, lbl, *maybe_w):
        C = x.shape[1]
        lbl2 = lbl.reshape(-1).astype(jnp.int32)
        x_y = jnp.take_along_axis(x, lbl2[:, None], axis=1)
        diff = jnp.maximum(margin - x_y + x, 0.0) ** p
        if maybe_w:
            diff = diff * maybe_w[0].reshape(-1)[lbl2][:, None]
        mask = jnp.arange(C)[None, :] != lbl2[:, None]
        loss = jnp.where(mask, diff, 0.0).sum(1) / C
        if reduction == "mean":
            return loss.mean()
        if reduction == "sum":
            return loss.sum()
        return loss

    args = (input, label) + (() if weight is None else (weight,))
    return apply_op(f, *args, op_name="multi_margin_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-family margin softmax (reference margin_cross_entropy /
    phi margin_cross_entropy kernel): target logit cosθ becomes
    cos(m1·θ + m2) − m3, everything scaled by s. Under model parallelism
    the reference computes over the class-sharded dim; here logits are
    logical global arrays so the plain formula applies."""

    def f(lg, lbl):
        lbl2 = lbl.reshape(-1).astype(jnp.int32)
        cos = jnp.clip(lg, -1.0, 1.0)
        theta = jnp.arccos(jnp.take_along_axis(cos, lbl2[:, None],
                                               axis=1)[:, 0])
        target = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(lbl2, lg.shape[1], dtype=lg.dtype)
        mod = cos * (1 - onehot) + target[:, None] * onehot
        z = mod * scale
        logp = jax.nn.log_softmax(z, axis=-1)
        nll = -jnp.take_along_axis(logp, lbl2[:, None], axis=1)[:, 0]
        sm = jnp.exp(logp)
        if reduction == "mean":
            loss = nll.mean()
        elif reduction == "sum":
            loss = nll.sum()
        else:
            loss = nll[:, None]
        return (loss, sm) if return_softmax else loss

    out = apply_op(f, logits, label, op_name="margin_cross_entropy")
    return out
