"""Common functionals: linear/dropout/embedding/pad/interpolate/one_hot...

Parity surface: python/paddle/nn/functional/common.py + input.py.
Everything lowers to lax ops XLA maps onto the MXU/VPU; dropout uses the
functional PRNG stream (core/random.py) so it stays jit-traceable.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.random import default_generator
from ...core.tensor import Tensor
from ...ops._helpers import unwrap

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout", "embedding",
    "one_hot", "pad", "zeropad2d", "interpolate", "upsample", "bilinear",
    "cosine_similarity", "pixel_shuffle", "pixel_unshuffle", "channel_shuffle",
    "label_smooth", "class_center_sample", "unfold", "fold",
]


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b; W is [in, out] (reference: nn/functional/common.py linear).

    The matmul is the MXU hot path — keep operands' trailing dims contiguous
    and let XLA pick the tiling.
    """
    if bias is None:
        return apply_op(lambda v, w: v @ w, x, weight, op_name="linear")
    return apply_op(lambda v, w, b: v @ w + b, x, weight, bias, op_name="linear")


def dropout(x, p=0.5, axis=None, training: bool = True, mode: str = "upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply_op(lambda v: v * (1.0 - p), x, op_name="dropout_infer")
        return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    k = default_generator.next_key()

    def f(v):
        shape = list(v.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = 1.0 - p
        mask = jax.random.bernoulli(k, keep, shape)
        if mode == "upscale_in_train":
            return jnp.where(mask, v / keep, 0.0).astype(v.dtype)
        return jnp.where(mask, v, 0.0).astype(v.dtype)

    return apply_op(f, x, op_name="dropout")


def dropout2d(x, p=0.5, training: bool = True, data_format: str = "NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=ax, training=training)


def dropout3d(x, p=0.5, training: bool = True, data_format: str = "NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training: bool = True, name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    k = default_generator.next_key()

    def f(v):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = 1.0 - p
        a = (keep + alpha_p**2 * keep * (1 - keep)) ** -0.5
        b = -a * alpha_p * (1 - keep)
        mask = jax.random.bernoulli(k, keep, v.shape)
        return (a * jnp.where(mask, v, alpha_p) + b).astype(v.dtype)

    return apply_op(f, x, op_name="alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse: bool = False, name=None):
    """Gather rows; padding_idx rows get zero grad (reference lookup_table_v2)."""

    def f(w, ids):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            pid = padding_idx if padding_idx >= 0 else w.shape[0] + padding_idx
            mask = (ids == pid)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    ids = unwrap(x)
    return apply_op(lambda w: f(w, ids), weight, op_name="embedding")


def one_hot(x, num_classes, name=None):
    v = unwrap(x)
    return Tensor(jax.nn.one_hot(v, num_classes, dtype=jnp.float32))


def _pad_width(pad_list, ndim, data_format):
    """paddle pad format: [left, right] pairs starting from the LAST spatial dim."""
    n = len(pad_list) // 2
    pw = [(0, 0)] * ndim
    # paddle order: pads apply to dims from last to first (W, H, D)
    if data_format.startswith("NC"):
        spatial = list(range(2, ndim))
    else:
        spatial = list(range(1, ndim - 1))
    for i in range(n):
        dim = spatial[-(i + 1)]
        pw[dim] = (int(pad_list[2 * i]), int(pad_list[2 * i + 1]))
    return pw


def pad(x, pad, mode: str = "constant", value: float = 0.0,
        data_format: str = "NCHW", pad_from_left_axis: bool = False, name=None):
    pad_list = [int(p) for p in (pad.tolist() if isinstance(pad, Tensor) else pad)]

    def f(v):
        if len(pad_list) == 2 * v.ndim:
            pw = [(pad_list[2 * i], pad_list[2 * i + 1]) for i in range(v.ndim)]
        else:
            pw = _pad_width(pad_list, v.ndim, data_format)
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(v, pw, mode="constant", constant_values=value)
        return jnp.pad(v, pw, mode=jmode)

    return apply_op(f, x, op_name="pad")


def zeropad2d(x, padding, data_format: str = "NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def label_smooth(label, prior_dist=None, epsilon: float = 0.1, name=None):
    def f(l):
        k = l.shape[-1]
        if prior_dist is not None:
            pd = unwrap(prior_dist)
            return (1 - epsilon) * l + epsilon * pd
        return (1 - epsilon) * l + epsilon / k

    return apply_op(f, label, op_name="label_smooth")


def cosine_similarity(x1, x2, axis: int = 1, eps: float = 1e-8):
    def f(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)

    return apply_op(f, x1, x2, op_name="cosine_similarity")


def bilinear(x1, x2, weight, bias=None, name=None):
    def f(a, b, w, *bi):
        # w: [out, in1, in2]
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bi:
            out = out + bi[0]
        return out

    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return apply_op(f, *args, op_name="bilinear")


def pixel_shuffle(x, upscale_factor: int, data_format: str = "NCHW", name=None):
    r = upscale_factor

    def f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))

    return apply_op(f, x, op_name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor: int, data_format: str = "NCHW", name=None):
    r = downscale_factor

    def f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            v = v.transpose(0, 1, 3, 5, 2, 4)
            return v.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = v.shape
        v = v.reshape(n, h // r, r, w // r, r, c)
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h // r, w // r, c * r * r)

    return apply_op(f, x, op_name="pixel_unshuffle")


def channel_shuffle(x, groups: int, data_format: str = "NCHW", name=None):
    def f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, groups, c // groups, h, w)
            return v.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, groups, c // groups)
        return v.transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)

    return apply_op(f, x, op_name="channel_shuffle")


def interpolate(x, size=None, scale_factor=None, mode: str = "nearest",
                align_corners: bool = False, align_mode: int = 0,
                data_format: str = "NCHW", name=None):
    """Resize via jax.image (reference: nn/functional/common.py interpolate)."""
    mode = mode.lower()
    jax_method = {"nearest": "nearest", "bilinear": "bilinear",
                  "trilinear": "trilinear", "bicubic": "bicubic",
                  "linear": "linear", "area": "linear"}[mode]

    def f(v):
        channel_last = not data_format.startswith("NC")
        nd = v.ndim - 2
        if channel_last:
            spatial = v.shape[1:-1]
        else:
            spatial = v.shape[2:]
        if size is not None:
            out_sp = [int(unwrap(s)) for s in (size if isinstance(size, (list, tuple)) else [size])]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * nd
            out_sp = [int(s * float(unwrap(f_))) for s, f_ in zip(spatial, sf)]
        if channel_last:
            out_shape = (v.shape[0], *out_sp, v.shape[-1])
        else:
            out_shape = (v.shape[0], v.shape[1], *out_sp)
        if mode == "nearest":
            # paddle/torch nearest: src = floor(i * in/out); with
            # align_corners the src is round(i * (in-1)/(out-1))
            return _resize_gather(v, out_shape, "nearest", align_corners,
                                  channel_last)
        if mode == "area":
            # paddle area == adaptive average pooling: out[i] averages
            # the source interval [floor(i*in/out), ceil((i+1)*in/out))
            # — a 2-tap linear sample is NOT a box filter
            return _adaptive_mean(v, out_shape, channel_last)
        if mode == "bicubic":
            # torch/paddle bicubic kernel is Keys a=-0.75; jax's cubic is
            # a=-0.5 — must be explicit for parity, both align modes
            return _resize_gather(v, out_shape, "cubic", align_corners,
                                  channel_last)
        if align_corners:
            return _resize_gather(v, out_shape, "linear", True,
                                  channel_last)
        # torch/paddle do NOT antialias on downsample; jax defaults to True
        return jax.image.resize(v, out_shape, method=jax_method,
                                antialias=False)

    return apply_op(f, x, op_name="interpolate")


def _cubic_weight(t, a=-0.75):
    """Keys cubic kernel with a=-0.75 (the torch/paddle/OpenCV choice)."""
    at = jnp.abs(t)
    return jnp.where(
        at <= 1.0, (a + 2.0) * at ** 3 - (a + 3.0) * at ** 2 + 1.0,
        jnp.where(at < 2.0,
                  a * at ** 3 - 5.0 * a * at ** 2 + 8.0 * a * at - 4.0 * a,
                  0.0))


def _adaptive_mean(v, out_shape, channel_last):
    """Separable adaptive-average resize (exact box means over the
    rectangular source regions — regions are per-axis intervals, so the
    nested per-axis means equal the region mean). Cumsum form handles
    uneven windows in O(n)."""
    if channel_last:
        in_sp, out_sp = v.shape[1:-1], out_shape[1:-1]
        sp_axes = list(range(1, v.ndim - 1))
    else:
        in_sp, out_sp = v.shape[2:], out_shape[2:]
        sp_axes = list(range(2, v.ndim))
    out = v
    for ax, insz, outsz in zip(sp_axes, in_sp, out_sp):
        i = jnp.arange(outsz)
        lo = jnp.floor(i * insz / outsz).astype(jnp.int32)
        hi = jnp.ceil((i + 1) * insz / outsz).astype(jnp.int32)
        c = jnp.cumsum(out.astype(jnp.float32), axis=ax)
        c = jnp.concatenate(
            [jnp.zeros_like(jnp.take(c, jnp.array([0]), axis=ax)), c],
            axis=ax)
        sums = jnp.take(c, hi, axis=ax) - jnp.take(c, lo, axis=ax)
        wsh = [1] * out.ndim
        wsh[ax] = outsz
        out = (sums / (hi - lo).astype(jnp.float32).reshape(wsh)).astype(
            v.dtype)
    return out


def _resize_gather(v, out_shape, kind, align_corners, channel_last):
    """Separable explicit-gather resize along every spatial axis.

    kind: 'nearest' (floor source), 'linear' (2 taps), 'cubic' (4 taps,
    a=-0.75). Source coordinates: align_corners maps corners to corners;
    otherwise half-pixel centers src = (i + 0.5)·in/out − 0.5."""
    if channel_last:
        in_sp, out_sp = v.shape[1:-1], out_shape[1:-1]
        sp_axes = list(range(1, v.ndim - 1))
    else:
        in_sp, out_sp = v.shape[2:], out_shape[2:]
        sp_axes = list(range(2, v.ndim))
    out = v
    for ax, insz, outsz in zip(sp_axes, in_sp, out_sp):
        i = jnp.arange(outsz, dtype=jnp.float32)
        if kind == "nearest":
            if align_corners and outsz > 1:
                src = jnp.round(i * (insz - 1) / (outsz - 1))
            else:
                src = jnp.floor(i * (insz / outsz))
            out = jnp.take(out, jnp.clip(src.astype(jnp.int32), 0,
                                         insz - 1), axis=ax)
            continue
        if align_corners:
            src = (i * (insz - 1) / (outsz - 1) if outsz > 1
                   else jnp.zeros_like(i))
        else:
            src = (i + 0.5) * (insz / outsz) - 0.5
        base = jnp.floor(src)
        frac = src - base
        taps = (0, 1) if kind == "linear" else (-1, 0, 1, 2)
        acc = None
        wsh = [1] * out.ndim
        wsh[ax] = outsz
        for k in taps:
            idx = jnp.clip(base.astype(jnp.int32) + k, 0, insz - 1)
            if kind == "linear":
                w = (1.0 - frac) if k == 0 else frac
            else:
                w = _cubic_weight(frac - k)
            term = jnp.take(out, idx, axis=ax) * w.reshape(wsh).astype(
                v.dtype)
            acc = term if acc is None else acc + term
        out = acc
    return out


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference unfold op): [N,C,H,W] → [N, C*kh*kw, L]."""
    k = _pair(kernel_sizes)
    s = _pair(strides)
    d = _pair(dilations)
    p = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]  # [ph, pw] -> [top,left,bottom,right]? paddle: [h,w] sym

    def f(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, ((0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])))
        patches = jax.lax.conv_general_dilated_patches(
            v, filter_shape=k, window_strides=s, padding="VALID",
            rhs_dilation=d, dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )  # [N, C*kh*kw, oh, ow]
        return patches.reshape(n, patches.shape[1], -1)

    return apply_op(f, x, op_name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """col2im — the VJP of unfold; implemented as transpose of the patch op."""
    out_sz = _pair(output_sizes)
    k = _pair(kernel_sizes)

    def f(v):
        n, ckk, L = v.shape
        c = ckk // (k[0] * k[1])
        zeros = jnp.zeros((n, c, out_sz[0], out_sz[1]), v.dtype)

        def unfold_fn(img):
            return unfold(Tensor(img), kernel_sizes, strides, paddings, dilations).value

        _, vjp = jax.vjp(unfold_fn, zeros)
        (out,) = vjp(v)
        return out

    return apply_op(f, x, op_name="fold")


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


def class_center_sample(label, num_classes, num_samples, group=None):
    """PartialFC class-center sampling (reference
    nn/functional/common.py:2034, arXiv:2010.05222): keep every positive
    class center present in ``label``, top up with uniformly sampled
    negatives to ``num_samples``, and remap labels into the sampled set.

    Returns ``(remapped_label, sampled_class_center)``. Eager-only: the
    output size is data-dependent (all positives are kept even beyond
    ``num_samples``), which has no static shape — call it on host data
    before the jitted step, like the reference calls it outside the fused
    margin-softmax kernel."""
    import numpy as np

    from ...core.random import default_generator
    from ...core.tensor import Tensor

    lv = np.asarray(label._value if isinstance(label, Tensor) else label)
    pos = np.unique(lv)
    if pos.size >= num_samples:
        sampled = pos
    else:
        neg_pool = np.setdiff1d(np.arange(num_classes, dtype=pos.dtype),
                                pos, assume_unique=True)
        import jax

        key = default_generator.next_key()
        perm = np.asarray(jax.random.permutation(key, neg_pool.size))
        extra = neg_pool[perm[: num_samples - pos.size]]
        sampled = np.sort(np.concatenate([pos, extra]))
    # remap each label to its index in the sampled (sorted) center list
    remapped = np.searchsorted(sampled, lv).astype(lv.dtype)
    return (Tensor(remapped), Tensor(sampled))
