"""Pooling functionals over ``lax.reduce_window``.

Parity: python/paddle/nn/functional/pooling.py (reference:
phi/kernels/funcs/pooling.cu). reduce_window is XLA's native windowed
reduction — maps directly to the VPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...core.autograd import apply_op
from ...core.tensor import Tensor
from ...ops._helpers import unwrap

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d",
    "max_pool1d", "max_pool2d", "max_pool3d",
    "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_avg_pool3d",
    "adaptive_max_pool1d", "adaptive_max_pool2d", "adaptive_max_pool3d",
    "max_unpool1d", "max_unpool2d", "max_unpool3d",
]


def _ntuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(x) for x in v)
    return tuple(int(v) for _ in range(n))


def _window_pads(padding, nd, ksize, strides, in_shape, ceil_mode):
    if isinstance(padding, str):
        return padding.upper()
    p = _ntuple(padding, nd) if not (isinstance(padding, (list, tuple)) and len(padding) == 2 * nd) \
        else None
    if p is not None:
        pads = [(x, x) for x in p]
    else:
        pl = [int(x) for x in padding]
        pads = [(pl[2 * i], pl[2 * i + 1]) for i in range(nd)]
    if ceil_mode:
        # extend right pad so the last partial window is included
        new = []
        for i, (lo, hi) in enumerate(pads):
            size = in_shape[i] + lo + hi
            rem = (size - ksize[i]) % strides[i]
            extra = (strides[i] - rem) % strides[i] if rem != 0 else 0
            new.append((lo, hi + extra))
        pads = new
    return pads


def _pool(x, ksize, strides, padding, nd, data_format, kind, ceil_mode=False,
          exclusive=True, divisor_override=None):
    channel_last = not data_format.startswith("NC")
    k = _ntuple(ksize, nd)
    s = _ntuple(strides if strides is not None else ksize, nd)

    def f(v):
        sp_off = 1 if channel_last else 2
        in_sp = v.shape[sp_off:sp_off + nd]
        pads = _window_pads(padding, nd, k, s, in_sp, ceil_mode)
        window = [1] * v.ndim
        stride_full = [1] * v.ndim
        for i in range(nd):
            window[sp_off + i] = k[i]
            stride_full[sp_off + i] = s[i]
        if isinstance(pads, str):
            pad_full = pads
        else:
            pad_full = [(0, 0)] * v.ndim
            for i in range(nd):
                pad_full[sp_off + i] = pads[i]
        if kind == "max":
            init = -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) else jnp.iinfo(v.dtype).min
            return lax.reduce_window(v, init, lax.max, window, stride_full, pad_full)
        # avg
        summed = lax.reduce_window(v, 0.0, lax.add, window, stride_full, pad_full)
        if divisor_override is not None:
            return summed / divisor_override
        if exclusive and not isinstance(pad_full, str):
            ones = jnp.ones(v.shape, v.dtype)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, stride_full, pad_full)
            return summed / counts
        denom = 1
        for i in range(nd):
            denom *= k[i]
        return summed / denom

    return apply_op(f, x, op_name=f"{kind}_pool{nd}d")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, "NCW", "avg", ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "avg",
                 ceil_mode, exclusive, divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "avg",
                 ceil_mode, exclusive, divisor_override)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    out = _pool(x, kernel_size, stride, padding, 1, "NCW", "max", ceil_mode)
    if return_mask:
        return out, _max_mask(x, out, kernel_size, stride, padding, 1, "NCW")
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, data_format, "max", ceil_mode)
    if return_mask:
        return out, _max_mask(x, out, kernel_size, stride, padding, 2, data_format)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, data_format, "max", ceil_mode)
    if return_mask:
        return out, _max_mask(x, out, kernel_size, stride, padding, 3, data_format)
    return out


def _max_mask(x, out, ksize, strides, padding, nd, data_format):
    """Flat spatial argmax indices per window (reference max_pool_with_index)."""
    channel_last = not data_format.startswith("NC")
    k = _ntuple(ksize, nd)
    s = _ntuple(strides if strides is not None else ksize, nd)
    v = unwrap(x)
    sp_off = 1 if channel_last else 2
    in_sp = v.shape[sp_off:sp_off + nd]
    flat_idx = jnp.arange(int(jnp.prod(jnp.asarray(in_sp))), dtype=jnp.int32).reshape(in_sp)
    bshape = [1] * v.ndim
    for i in range(nd):
        bshape[sp_off + i] = in_sp[i]
    flat_idx = jnp.broadcast_to(flat_idx.reshape(bshape), v.shape)

    pads = _window_pads(padding, nd, k, s, in_sp, False)
    window = [1] * v.ndim
    stride_full = [1] * v.ndim
    for i in range(nd):
        window[sp_off + i] = k[i]
        stride_full[sp_off + i] = s[i]
    if isinstance(pads, str):
        pad_full = pads
    else:
        pad_full = [(0, 0)] * v.ndim
        for i in range(nd):
            pad_full[sp_off + i] = pads[i]

    def select(a, b):
        av, ai = a
        bv, bi = b
        pick = av >= bv
        return jnp.where(pick, av, bv), jnp.where(pick, ai, bi)

    init_v = jnp.asarray(-jnp.inf, v.dtype) if jnp.issubdtype(v.dtype, jnp.floating) \
        else jnp.asarray(jnp.iinfo(v.dtype).min, v.dtype)
    vals, idxs = lax.reduce_window(
        (v, flat_idx), (init_v, jnp.asarray(-1, jnp.int32)),
        select, window, stride_full, pad_full,
    )
    return Tensor(idxs)


def _adaptive_windows(in_sz, out_sz):
    import numpy as np

    starts = (np.arange(out_sz) * in_sz) // out_sz
    ends = -(-((np.arange(out_sz) + 1) * in_sz) // out_sz)  # ceil div
    return starts, ends


def _adaptive_pool(x, output_size, nd, data_format, kind):
    channel_last = not data_format.startswith("NC")
    out_sp = _ntuple(output_size, nd)

    def f(v):
        sp_off = 1 if channel_last else 2
        res = v
        for i in range(nd):
            ax = sp_off + i
            insz = res.shape[ax]
            outsz = out_sp[i]
            if outsz == insz:
                continue
            if insz % outsz == 0:
                # uniform windows: reshape-reduce (fast path, static)
                kwin = insz // outsz
                new_shape = res.shape[:ax] + (outsz, kwin) + res.shape[ax + 1:]
                r = res.reshape(new_shape)
                res = jnp.max(r, axis=ax + 1) if kind == "max" else jnp.mean(r, axis=ax + 1)
            else:
                starts, ends = _adaptive_windows(insz, outsz)
                pieces = []
                for j in range(outsz):
                    sl = [slice(None)] * res.ndim
                    sl[ax] = slice(int(starts[j]), int(ends[j]))
                    seg = res[tuple(sl)]
                    red = jnp.max(seg, axis=ax, keepdims=True) if kind == "max" \
                        else jnp.mean(seg, axis=ax, keepdims=True)
                    pieces.append(red)
                res = jnp.concatenate(pieces, axis=ax)
        return res

    return apply_op(f, x, op_name=f"adaptive_{kind}_pool{nd}d")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "NCW", "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format, "avg")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format, "avg")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 1, "NCW", "max")
    return (out, None) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 2, "NCHW", "max")
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 3, "NCDHW", "max")
    return (out, None) if return_mask else out


def _max_unpool(x, indices, kernel_size, stride, padding, nd, output_size, data_format):
    k = _ntuple(kernel_size, nd)
    s = _ntuple(stride if stride is not None else kernel_size, nd)

    def f(v, idx):
        n, c = v.shape[0], v.shape[1]
        in_sp = v.shape[2:]
        if output_size is not None:
            out_sp = tuple(int(unwrap(o)) for o in output_size)[-nd:]
        else:
            p = _ntuple(padding, nd)
            out_sp = tuple((in_sp[i] - 1) * s[i] - 2 * p[i] + k[i] for i in range(nd))
        flat_out = 1
        for o in out_sp:
            flat_out *= o
        vf = v.reshape(n, c, -1)
        idxf = idx.reshape(n, c, -1)
        out = jnp.zeros((n, c, flat_out), v.dtype)
        bidx = jnp.arange(n)[:, None, None]
        cidx = jnp.arange(c)[None, :, None]
        out = out.at[bidx, cidx, idxf].set(vf)
        return out.reshape((n, c) + out_sp)

    idx_arr = unwrap(indices)
    return apply_op(lambda v: f(v, idx_arr), x, op_name=f"max_unpool{nd}d")


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, 1, output_size, data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, 2, output_size, data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, 3, output_size, data_format)
