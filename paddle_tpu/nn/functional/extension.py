"""Misc functionals: distance, masks, vision warps, temporal shift.

Parity: python/paddle/nn/functional/{distance,extension,vision}.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.tensor import Tensor
from ...ops._helpers import unwrap

__all__ = [
    "pairwise_distance", "pdist", "sequence_mask", "diag_embed",
    "temporal_shift", "affine_grid", "grid_sample", "npair_loss",
]


def pairwise_distance(x, y, p: float = 2.0, epsilon: float = 1e-6,
                      keepdim: bool = False, name=None):
    def f(a, b):
        d = a - b + epsilon
        return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)

    return apply_op(f, x, y, op_name="pairwise_distance")


def pdist(x, p: float = 2.0, name=None):
    def f(v):
        n = v.shape[0]
        diff = v[:, None, :] - v[None, :, :]
        s = jnp.sum(jnp.abs(diff) ** p, axis=-1)
        iu = jnp.triu_indices(n, k=1)
        # root AFTER slicing off the diagonal: d(s^(1/p))/ds at the
        # diagonal's exact 0 is inf, and 0-cotangent * inf = NaN would
        # poison the whole gradient (r5 check_grad sweep finding)
        return s[iu] ** (1.0 / p)

    return apply_op(f, x, op_name="pdist")


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    v = unwrap(x)
    ml = int(maxlen) if maxlen is not None else int(jnp.max(v))
    from ...core.dtype import convert_dtype

    out = (jnp.arange(ml) < v[..., None]).astype(convert_dtype(dtype))
    return Tensor(out)


def diag_embed(input, offset: int = 0, dim1: int = -2, dim2: int = -1):
    def f(v):
        last = v.shape[-1]
        size = last + abs(offset)
        out = jnp.zeros(v.shape[:-1] + (size, size), v.dtype)
        idx = jnp.arange(last)
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(v)
        nd = out.ndim
        d1 = dim1 % nd
        d2 = dim2 % nd
        if (d1, d2) != (nd - 2, nd - 1):
            perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
            # insert the two diag dims at requested positions
            order = {}
            order[d1] = nd - 2
            order[d2] = nd - 1
            rest = iter(perm)
            final = [order[i] if i in order else next(rest) for i in range(nd)]
            out = jnp.transpose(out, final)
        return out

    return apply_op(f, input, op_name="diag_embed")


def temporal_shift(x, seg_num: int, shift_ratio: float = 0.25,
                   data_format: str = "NCHW", name=None):
    def f(v):
        if data_format == "NHWC":
            v = jnp.transpose(v, (0, 3, 1, 2))
        nt, c, h, w = v.shape
        n = nt // seg_num
        v = v.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        back = jnp.concatenate([v[:, 1:, :c1], jnp.zeros_like(v[:, :1, :c1])], axis=1)
        fwd = jnp.concatenate([jnp.zeros_like(v[:, :1, c1:c2]), v[:, :-1, c1:c2]], axis=1)
        keep = v[:, :, c2:]
        out = jnp.concatenate([back, fwd, keep], axis=2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply_op(f, x, op_name="temporal_shift")


def affine_grid(theta, out_shape, align_corners: bool = True, name=None):
    shape = [int(unwrap(s)) for s in out_shape]

    def f(th):
        n, _, h, w = shape if len(shape) == 4 else (shape[0], shape[1], shape[2], shape[3])

        def coords(size):
            if align_corners:
                return jnp.linspace(-1.0, 1.0, size)
            step = 2.0 / size
            return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

        ys = coords(h)
        xs = coords(w)
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # [h*w, 3]
        out = jnp.einsum("nij,pj->npi", th.astype(jnp.float32), base)
        return out.reshape(n, h, w, 2).astype(th.dtype)

    return apply_op(f, theta, op_name="affine_grid")


def grid_sample(x, grid, mode: str = "bilinear", padding_mode: str = "zeros",
                align_corners: bool = True, name=None):
    def f(v, g):
        n, c, h, w = v.shape
        gx = g[..., 0]
        gy = g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        if padding_mode == "reflection":
            # fold the FLOAT coordinate back into range before any tap
            # math (torch reflect_coordinates): align_corners reflects
            # about the corner centers [0, size-1]; otherwise about the
            # half-pixel borders [-0.5, size-0.5]
            def reflect(coord, size):
                if size == 1:
                    return jnp.zeros_like(coord)
                if align_corners:
                    m = 2.0 * (size - 1)
                    t = jnp.mod(jnp.abs(coord), m)
                    return jnp.where(t > size - 1, m - t, t)
                m = 2.0 * size
                t = jnp.mod(jnp.abs(coord + 0.5), m)
                t = jnp.where(t > size, m - t, t)
                return jnp.clip(t - 0.5, 0.0, size - 1.0)

            fx = reflect(fx, w)
            fy = reflect(fy, h)

        def sample(ix, iy):
            valid = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
            cx = jnp.clip(ix, 0, w - 1)
            cy = jnp.clip(iy, 0, h - 1)
            out = v[jnp.arange(n)[:, None, None], :, cy, cx]  # [n, gh, gw, c]
            if padding_mode == "zeros":
                out = jnp.where(valid[..., None], out, 0.0)
            return out

        if mode == "nearest":
            out = sample(jnp.round(fx).astype(jnp.int32), jnp.round(fy).astype(jnp.int32))
        else:
            x0 = jnp.floor(fx).astype(jnp.int32)
            y0 = jnp.floor(fy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1
            wa = (x1 - fx) * (y1 - fy)
            wb = (x1 - fx) * (fy - y0)
            wc = (fx - x0) * (y1 - fy)
            wd = (fx - x0) * (fy - y0)
            out = (sample(x0, y0) * wa[..., None] + sample(x0, y1) * wb[..., None]
                   + sample(x1, y0) * wc[..., None] + sample(x1, y1) * wd[..., None])
        return jnp.transpose(out, (0, 3, 1, 2))  # back to NCHW

    return apply_op(f, x, grid, op_name="grid_sample")


def npair_loss(anchor, positive, labels, l2_reg: float = 0.002):
    lbl = unwrap(labels)

    def f(a, p):
        l2 = l2_reg * (jnp.sum(a * a) + jnp.sum(p * p)) / a.shape[0]
        sim = a @ p.T
        y = (lbl[:, None] == lbl[None, :]).astype(sim.dtype)
        y = y / jnp.sum(y, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(y * logp, axis=1))
        return ce + l2

    return apply_op(f, anchor, positive, op_name="npair_loss")
