"""Attention functionals: flash / scaled-dot-product / sparse-block.

Parity: python/paddle/nn/functional/flash_attention.py:125 (reference dynloads
libflashattn, phi/kernels/gpu/flash_attn_kernel.cu:213). On TPU the fast path
is a Pallas splash/flash kernel (paddle_tpu.ops.pallas); this module routes to
it on TPU backends and falls back to the XLA softmax(QK^T)V composition —
which XLA already fuses well — on CPU.

Layout note: paddle's flash_attention takes [batch, seqlen, nheads, head_dim].
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.random import default_generator
from ...core.tensor import Tensor
from ...ops._helpers import unwrap

__all__ = [
    "flash_attention", "flash_attn_unpadded", "scaled_dot_product_attention",
    "sdp_kernel", "sparse_attention",
]


def _use_pallas() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _sdpa_ref(q, k, v, mask=None, causal=False, dropout_p=0.0, scale=None,
              dropout_key=None):
    """[B, S, H, D] reference composition; f32 softmax accumulation.
    GQA allowed: K/V with fewer heads are repeated up to Q's head count."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # [B, H, S, D]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * s
    scores = scores.astype(jnp.float32)
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        cmask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(cmask, scores, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -jnp.inf)
        else:
            scores = scores + mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)  # back to [B, S, H, D]


def flash_attention(query, key, value, dropout: float = 0.0, causal: bool = False,
                    return_softmax: bool = False, fixed_seed_offset=None,
                    rng_name: str = "", training: bool = True, name=None):
    """paddle.nn.functional.flash_attention parity. Returns (out, softmax).

    GQA allowed (key/value with fewer heads). Routing:
    - TPU, block-divisible seq lens → own Pallas flash kernel
      (ops/flash_attention_kernel.py), dropout applied IN-KERNEL via a
      counter-based RNG (no second attention pass, no S² buffer);
    - CPU with dropout → the same kernel in interpret mode (so tests
      exercise the real dropout code path);
    - otherwise → XLA reference composition.
    """
    p = dropout if training else 0.0
    from ...ops.flash_attention_kernel import supports
    from ...ops.pallas import flash_attention as pallas_flash

    sq, sk = query.shape[1], key.shape[1]
    use_kernel = supports(sq, sk) and (_use_pallas() or p > 0.0)
    if use_kernel:
        if p > 0.0:
            seed = jax.random.randint(default_generator.next_key(), (1,),
                                      0, 2**31 - 1, dtype=jnp.int32)
        else:
            seed = None
        out = apply_op(
            lambda q, k, v: pallas_flash(q, k, v, causal=causal,
                                         dropout_p=p, seed=seed),
            query, key, value, op_name="flash_attention")
    else:
        dk = default_generator.next_key() if p > 0.0 else None
        out = apply_op(
            lambda q, k, v: _sdpa_ref(q, k, v, causal=causal, dropout_p=p,
                                      dropout_key=dk),
            query, key, value, op_name="flash_attention")
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale: float,
                        dropout: float = 0.0, causal: bool = False,
                        return_softmax: bool = False, fixed_seed_offset=None,
                        rng_name: str = "", training: bool = True, name=None):
    """Varlen flash attention: [total_tokens, H, D] + cu_seqlens.

    TPU-native form: segment-masked dense attention (ragged batches become a
    segment-id mask — dynamic shapes are hostile to XLA, masks are free).
    """
    cq = unwrap(cu_seqlens_q)
    ck = unwrap(cu_seqlens_k)

    def f(q, k, v):
        tq = q.shape[0]
        tk = k.shape[0]
        seg_q = jnp.cumsum(
            jnp.zeros(tq, jnp.int32).at[cq[1:-1]].add(1)) if cq.shape[0] > 2 else jnp.zeros(tq, jnp.int32)
        seg_k = jnp.cumsum(
            jnp.zeros(tk, jnp.int32).at[ck[1:-1]].add(1)) if ck.shape[0] > 2 else jnp.zeros(tk, jnp.int32)
        scores = jnp.einsum("qhd,khd->hqk", q, k) * scale
        scores = scores.astype(jnp.float32)
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(tq) - jnp.take(cq, seg_q)
            pos_k = jnp.arange(tk) - jnp.take(ck, seg_k)
            mask = mask & (pos_q[:, None] >= pos_k[None, :])
        scores = jnp.where(mask[None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        probs = jnp.where(mask[None], probs, 0.0)
        return jnp.einsum("hqk,khd->qhd", probs, v)

    out = apply_op(f, query, key, value, op_name="flash_attn_unpadded")
    return out, None


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p: float = 0.0, is_causal: bool = False,
                                 training: bool = True, name=None):
    """paddle layout [B, S, H, D]; mask broadcastable to [B, H, Sq, Sk]."""
    dk = default_generator.next_key() if (dropout_p > 0.0 and training) else None
    m = unwrap(attn_mask) if attn_mask is not None else None

    def f(q, k, v):
        return _sdpa_ref(q, k, v, mask=m, causal=is_causal,
                         dropout_p=dropout_p if training else 0.0, dropout_key=dk)

    return apply_op(f, query, key, value, op_name="scaled_dot_product_attention")


class sdp_kernel:
    """Context manager selecting attention backends (API parity; routing is
    automatic on TPU)."""

    def __init__(self, enable_flash=True, enable_math=True, enable_mem_efficient=True):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """CSR-pattern attention (reference nn/functional/sparse_attention.py,
    phi/kernels/sparse/gpu/sparse_attention — computes ONLY the stored
    (q, k) pairs).

    Gather path (default): per-row key/value gathers at static capacity
    R = max row nnz (rounded to the 8-sublane tile), scores [bh, s, R] —
    memory O(s·R·d), never the dense [s, s] score matrix, matching the
    reference kernel's point. Falls back to the dense masked form when
    the pattern is near-dense (R > s/2 — the gather would cost more than
    it saves) or when the CSR arrays are tracers (row capacity must be
    static)."""
    offs = unwrap(sparse_csr_offset)
    cols = unwrap(sparse_csr_columns)

    def dense_f(q, k, v):
        b, h, s, d = q.shape
        # CSR pattern → boolean mask by scattering (vectorized over batch*head)
        bh = b * h
        offs2 = offs.reshape(bh, s + 1)
        cols2 = cols.reshape(bh, -1)
        nnz = cols2.shape[-1]
        pos = jnp.arange(nnz)
        row_of = jax.vmap(
            lambda o: jnp.searchsorted(o, pos, side="right") - 1
        )(offs2)  # [bh, nnz]
        valid = pos[None, :] < offs2[:, -1:]
        bidx = jnp.repeat(jnp.arange(bh)[:, None], nnz, 1)
        mask2 = jnp.zeros((bh, s, s), bool)
        mask2 = mask2.at[bidx, jnp.clip(row_of, 0, s - 1), cols2].max(valid)
        mask = mask2.reshape(b, h, s, s)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (d ** 0.5)
        scores = jnp.where(mask, scores.astype(jnp.float32), -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        probs = jnp.where(mask, probs, 0.0).astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, v)

    def gather_f(q, k, v, R):
        b, h, s, d = q.shape
        bh = b * h
        offs2 = offs.reshape(bh, s + 1)
        cols2 = cols.reshape(bh, -1)
        lens = offs2[:, 1:] - offs2[:, :-1]                 # [bh, s]
        r = jnp.arange(R)
        base = offs2[:, :-1, None] + r[None, None, :]       # [bh, s, R]
        nnz = cols2.shape[-1]
        idx = jnp.take_along_axis(
            cols2[:, None, :], jnp.clip(base, 0, max(nnz - 1, 0)),
            axis=2)                                          # [bh, s, R]
        valid = r[None, None, :] < lens[:, :, None]
        q2 = q.reshape(bh, s, d)
        k2 = k.reshape(bh, s, d)
        v2 = v.reshape(bh, s, d)
        kg = jax.vmap(lambda kk, ii: kk[ii])(k2, idx)        # [bh, s, R, d]
        vg = jax.vmap(lambda vv, ii: vv[ii])(v2, idx)
        scores = jnp.einsum("bqd,bqrd->bqr", q2.astype(jnp.float32),
                            kg.astype(jnp.float32)) / (d ** 0.5)
        scores = jnp.where(valid, scores, -jnp.inf)
        m = jnp.max(scores, axis=-1, keepdims=True)
        p = jnp.exp(scores - jnp.where(jnp.isfinite(m), m, 0.0))
        p = jnp.where(valid, p, 0.0)
        denom = jnp.maximum(jnp.sum(p, -1, keepdims=True), 1e-30)
        out = jnp.einsum("bqr,bqrd->bqd", (p / denom).astype(v.dtype), vg)
        return out.reshape(b, h, s, d)

    # static row capacity decides the path; tracers can't give one
    R = None
    try:
        import numpy as _np

        o = _np.asarray(offs)
        R = int((o.reshape(-1, o.shape[-1])[:, 1:]
                 - o.reshape(-1, o.shape[-1])[:, :-1]).max())
    except (TypeError, jax.errors.TracerArrayConversionError,
            jax.errors.ConcretizationTypeError):
        pass

    def f(q, k, v):
        s = q.shape[2]
        if R is not None and 0 < R <= s // 2:
            # round capacity to the sublane tile so the gather lanes align
            return gather_f(q, k, v, min(s, -(-R // 8) * 8))
        return dense_f(q, k, v)

    return apply_op(f, query, key, value, op_name="sparse_attention")
