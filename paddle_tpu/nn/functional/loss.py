"""Loss functionals.

Parity: python/paddle/nn/functional/loss.py (reference kernels:
phi/kernels/gpu/cross_entropy_kernel.cu, funcs/cross_entropy.cu).
cross_entropy fuses log_softmax+NLL the way the reference's
softmax_with_cross_entropy kernel does — one traced graph, XLA fuses it.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.tensor import Tensor
from ...ops._helpers import unwrap

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "nll_loss", "l1_loss", "mse_loss",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "hinge_embedding_loss",
    "cosine_embedding_loss", "ctc_loss", "triplet_margin_loss",
    "triplet_margin_with_distance_loss", "multi_label_soft_margin_loss",
    "soft_margin_loss", "sigmoid_focal_loss", "dice_loss", "log_loss",
    "square_error_cost", "poisson_nll_loss", "gaussian_nll_loss",
]


def _reduce(v, reduction: str):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index: int = -100,
                  reduction: str = "mean", soft_label: bool = False, axis: int = -1,
                  use_softmax: bool = True, label_smoothing: float = 0.0, name=None):
    lbl = unwrap(label)
    w = unwrap(weight) if weight is not None else None

    def f(logits):
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(
            jnp.maximum(logits, 1e-30))
        n_classes = logits.shape[axis]
        if soft_label or (lbl.ndim == logits.ndim and lbl.shape == logits.shape):
            tgt = lbl.astype(logp.dtype)
            if label_smoothing > 0.0:
                tgt = (1 - label_smoothing) * tgt + label_smoothing / n_classes
            loss = -jnp.sum(tgt * logp, axis=axis)
            mask = None
        else:
            ids = lbl
            if ids.ndim == logits.ndim:  # trailing 1 dim
                ids = jnp.squeeze(ids, axis)
            mask = ids != ignore_index
            safe = jnp.where(mask, ids, 0).astype(jnp.int32)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe, axis), axis=axis
            ).squeeze(axis)
            if label_smoothing > 0.0:
                smooth = jnp.mean(logp, axis=axis)
                picked = (1 - label_smoothing) * picked + label_smoothing * smooth
            loss = -jnp.where(mask, picked, 0.0)
            if w is not None:
                wsel = jnp.where(mask, jnp.take(w, safe), 0.0)
                loss = loss * wsel
                if reduction == "mean":
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(wsel), 1e-12)
        if reduction == "mean" and mask is not None:
            denom = jnp.maximum(jnp.sum(mask.astype(logp.dtype)), 1.0)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    return apply_op(f, input, op_name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label: bool = False,
                               ignore_index: int = -100, numeric_stable_mode: bool = True,
                               return_softmax: bool = False, axis: int = -1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    # reference keeps a trailing dim
    from ...ops.manipulation import unsqueeze

    loss = unsqueeze(loss, axis)
    if return_softmax:
        from .activation import softmax

        return loss, softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction: str = "mean", name=None):
    lbl = unwrap(label)
    w = unwrap(weight) if weight is not None else None

    def f(p):
        eps = 1e-12
        loss = -(lbl * jnp.log(jnp.maximum(p, eps))
                 + (1 - lbl) * jnp.log(jnp.maximum(1 - p, eps)))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    return apply_op(f, input, op_name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction: str = "mean",
                                     pos_weight=None, name=None):
    lbl = unwrap(label)
    w = unwrap(weight) if weight is not None else None
    pw = unwrap(pos_weight) if pos_weight is not None else None

    def f(z):
        # stable: max(z,0) - z*y + log(1+exp(-|z|)); pos_weight scales the y term
        base = jnp.maximum(z, 0) - z * lbl + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if pw is not None:
            log_weight = 1 + (pw - 1) * lbl
            base = jnp.maximum(z, 0) - z * lbl + log_weight * jnp.log1p(jnp.exp(-jnp.abs(z)))
            # full form: loss = (1-y)z + log_weight*(log(1+exp(-|z|)) + max(-z,0))
            base = (1 - lbl) * z + log_weight * (jnp.log1p(jnp.exp(-jnp.abs(z))) + jnp.maximum(-z, 0))
        if w is not None:
            base = base * w
        return _reduce(base, reduction)

    return apply_op(f, logit, op_name="bce_with_logits")


def nll_loss(input, label, weight=None, ignore_index: int = -100,
             reduction: str = "mean", name=None):
    lbl = unwrap(label)
    w = unwrap(weight) if weight is not None else None

    def f(logp):
        mask = lbl != ignore_index
        safe = jnp.where(mask, lbl, 0).astype(jnp.int32)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
        loss = -jnp.where(mask, picked, 0.0)
        if w is not None:
            wsel = jnp.where(mask, jnp.take(w, safe), 0.0)
            loss = loss * wsel
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(wsel), 1e-12)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(mask.astype(logp.dtype)), 1.0)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    return apply_op(f, input, op_name="nll_loss")


def l1_loss(input, label, reduction: str = "mean", name=None):
    return apply_op(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                    input, label, op_name="l1_loss")


def mse_loss(input, label, reduction: str = "mean", name=None):
    return apply_op(lambda a, b: _reduce((a - b) ** 2, reduction),
                    input, label, op_name="mse_loss")


def square_error_cost(input, label):
    return apply_op(lambda a, b: (a - b) ** 2, input, label, op_name="square_error_cost")


def smooth_l1_loss(input, label, reduction: str = "mean", delta: float = 1.0, name=None):
    def f(a, b):
        d = a - b
        abs_d = jnp.abs(d)
        loss = jnp.where(abs_d < delta, 0.5 * d * d, delta * (abs_d - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply_op(f, input, label, op_name="smooth_l1_loss")


def kl_div(input, label, reduction: str = "mean", log_target: bool = False, name=None):
    def f(logp, tgt):
        if log_target:
            loss = jnp.exp(tgt) * (tgt - logp)
        else:
            loss = jnp.where(tgt > 0, tgt * (jnp.log(jnp.maximum(tgt, 1e-12)) - logp), 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return apply_op(f, input, label, op_name="kl_div")


def margin_ranking_loss(input, other, label, margin: float = 0.0,
                        reduction: str = "mean", name=None):
    def f(a, b, y):
        loss = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(loss, reduction)

    return apply_op(f, input, other, label, op_name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin: float = 1.0, reduction: str = "mean", name=None):
    def f(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)

    return apply_op(f, input, label, op_name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin: float = 0.0,
                          reduction: str = "mean", name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return apply_op(f, input1, input2, label, op_name="cosine_embedding_loss")


def soft_margin_loss(input, label, reduction: str = "mean", name=None):
    def f(a, y):
        return _reduce(jnp.log1p(jnp.exp(-y * a)), reduction)

    return apply_op(f, input, label, op_name="soft_margin_loss")


def multi_label_soft_margin_loss(input, label, weight=None, reduction: str = "mean", name=None):
    w = unwrap(weight) if weight is not None else None

    def f(z, y):
        loss = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        if w is not None:
            loss = loss * w
        loss = jnp.mean(loss, axis=-1)
        return _reduce(loss, reduction)

    return apply_op(f, input, label, op_name="multi_label_soft_margin_loss")


def triplet_margin_loss(input, positive, negative, margin: float = 1.0, p: float = 2.0,
                        epsilon: float = 1e-6, swap: bool = False,
                        reduction: str = "mean", name=None):
    def f(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos) ** p + epsilon, -1) ** (1 / p)
        dn = jnp.sum(jnp.abs(a - neg) ** p + epsilon, -1) ** (1 / p)
        if swap:
            dn2 = jnp.sum(jnp.abs(pos - neg) ** p + epsilon, -1) ** (1 / p)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply_op(f, input, positive, negative, op_name="triplet_margin_loss")


def triplet_margin_with_distance_loss(input, positive, negative, distance_function=None,
                                      margin: float = 1.0, swap: bool = False,
                                      reduction: str = "mean", name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin,
                                   swap=swap, reduction=reduction)
    dp = distance_function(input, positive)
    dn = distance_function(input, negative)
    if swap:
        from ...ops.math import minimum

        dn = minimum(dn, distance_function(positive, negative))

    def f(dpv, dnv):
        return _reduce(jnp.maximum(dpv - dnv + margin, 0.0), reduction)

    return apply_op(f, dp, dn, op_name="triplet_margin_with_distance_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha: float = 0.25,
                       gamma: float = 2.0, reduction: str = "sum", name=None):
    norm = unwrap(normalizer) if normalizer is not None else None

    def f(z, y):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        loss = ce * ((1 - p_t) ** gamma)
        if alpha >= 0:
            a_t = alpha * y + (1 - alpha) * (1 - y)
            loss = a_t * loss
        if norm is not None:
            loss = loss / norm
        return _reduce(loss, reduction)

    return apply_op(f, logit, label, op_name="sigmoid_focal_loss")


def dice_loss(input, label, epsilon: float = 1e-5, name=None):
    lbl = unwrap(label)

    def f(p):
        y = jax.nn.one_hot(jnp.squeeze(lbl, -1), p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(y, axis=reduce_dims)
        dice = (2 * inter + epsilon) / (union + epsilon)
        return jnp.mean(1 - dice)

    return apply_op(f, input, op_name="dice_loss")


def log_loss(input, label, epsilon: float = 1e-4, name=None):
    def f(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)

    return apply_op(f, input, label, op_name="log_loss")


def poisson_nll_loss(input, label, log_input: bool = True, full: bool = False,
                     epsilon: float = 1e-8, reduction: str = "mean", name=None):
    def f(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y) - y + 0.5 * jnp.log(2 * jnp.pi * y)
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)

    return apply_op(f, input, label, op_name="poisson_nll_loss")


def gaussian_nll_loss(input, label, variance, full: bool = False,
                      epsilon: float = 1e-6, reduction: str = "mean", name=None):
    def f(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            loss = loss + 0.5 * jnp.log(2 * jnp.asarray(jnp.pi, mu.dtype))
        return _reduce(loss, reduction)

    return apply_op(f, input, label, variance, op_name="gaussian_nll_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank: int = 0,
             reduction: str = "mean", norm_by_times: bool = False):
    """CTC via the standard alpha-recursion in log space, vectorized with
    lax.scan over time (reference: warpctc; here it is a traced XLA program)."""
    lbl = unwrap(labels)
    in_len = unwrap(input_lengths)
    lb_len = unwrap(label_lengths)

    def f(lp):
        # lp: [T, B, C] UNNORMALIZED logits (paddle layout + contract:
        # "softmax with CTC" — warpctc normalizes internally, reference
        # loss.py:1770; torch by contrast takes log-probs)
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, B, C = lp.shape
        S = lbl.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lbl.astype(jnp.int32))
        L = 2 * lb_len.astype(jnp.int32) + 1  # extended lengths

        neg_inf = jnp.asarray(-1e30, lp.dtype)
        alpha0 = jnp.full((B, 2 * S + 1), neg_inf, lp.dtype)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lbl = lp[0][jnp.arange(B), ext[:, 1]]
        alpha0 = alpha0.at[:, 1].set(jnp.where(lb_len > 0, first_lbl, neg_inf))

        same = jnp.pad(ext[:, 2:] == ext[:, :-2], ((0, 0), (2, 0)),
                       constant_values=True)

        def step(alpha, lp_t):
            a_prev = alpha
            a_shift1 = jnp.pad(alpha[:, :-1], ((0, 0), (1, 0)), constant_values=-1e30)
            a_shift2 = jnp.pad(alpha[:, :-2], ((0, 0), (2, 0)), constant_values=-1e30)
            a_shift2 = jnp.where(same, neg_inf, a_shift2)
            merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2)
            emit = lp_t[jnp.arange(B)[:, None], ext]
            return merged + emit, None

        def scan_step(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, lp[t])
            # only advance while t < input_length
            keep = (t < in_len)[:, None]
            return jnp.where(keep, new_alpha, alpha), None

        alpha, _ = jax.lax.scan(scan_step, alpha0, jnp.arange(1, T))
        idx_last = jnp.clip(L - 1, 0, 2 * S)
        idx_prev = jnp.clip(L - 2, 0, 2 * S)
        ll = jnp.logaddexp(
            alpha[jnp.arange(B), idx_last], alpha[jnp.arange(B), idx_prev]
        )
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lb_len.astype(lp.dtype), 1.0))
        return _reduce(loss, reduction)

    return apply_op(f, log_probs, op_name="ctc_loss")
