"""Convolution functionals over ``lax.conv_general_dilated``.

Parity: python/paddle/nn/functional/conv.py (reference kernels:
phi/kernels/gpu/conv_kernel.cu + cudnn autotuning). On TPU, XLA lowers
conv_general_dilated straight onto the MXU — algorithm choice, layout
(NCHW→XLA-internal), and fusion are the compiler's job, so there is no
cudnn-workspace/autotune machinery to rebuild.
"""
from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ...core.autograd import apply_op
from ...ops._helpers import unwrap

__all__ = [
    "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
]


def _ntuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(x) for x in v)
    return tuple(int(v) for _ in range(n))


def _resolve_padding(padding, nd, strides, dilations, kernel):
    """paddle padding: int | list | 'SAME' | 'VALID'. Returns lax-style pairs or str."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)):
        p = [int(x) for x in padding]
        if len(p) == nd:
            return [(x, x) for x in p]
        if len(p) == 2 * nd:
            # [before0, after0, before1, after1, ...] paddle allows both
            return [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
        if len(p) == 1:
            return [(p[0], p[0])] * nd
    return [(int(padding), int(padding))] * nd


def _dim_numbers(nd, channel_last):
    if nd == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if nd == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, nd, data_format):
    channel_last = not data_format.startswith("NC")
    strides = _ntuple(stride, nd)
    dilations = _ntuple(dilation, nd)
    dn = _dim_numbers(nd, channel_last)
    pad = _resolve_padding(padding, nd, strides, dilations, None)

    def f(v, w, *b):
        # paddle weight layout is [out_c, in_c/groups, *k] = OI... always
        if channel_last:
            perm = tuple(range(2, 2 + nd)) + (1, 0)  # OIHW -> HWIO
            w_ = jnp.transpose(w, perm)
        else:
            w_ = w
        out = lax.conv_general_dilated(
            v, w_, window_strides=strides, padding=pad,
            rhs_dilation=dilations, dimension_numbers=dn,
            feature_group_count=groups,
        )
        if b:
            bias_shape = [1] * out.ndim
            bias_shape[-1 if channel_last else 1] = b[0].shape[0]
            out = out + b[0].reshape(bias_shape)
        return out

    args = (x, weight) + (() if bias is None else (bias,))
    return apply_op(f, *args, op_name=f"conv{nd}d")


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, fmt)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, groups,
                    dilation, nd, data_format, output_size):
    channel_last = not data_format.startswith("NC")
    strides = _ntuple(stride, nd)
    dilations = _ntuple(dilation, nd)
    out_pad = _ntuple(output_padding, nd) if output_padding is not None else (0,) * nd
    dn = _dim_numbers(nd, channel_last)

    def f(v, w, *b):
        # paddle transpose-conv weight layout: [in_c, out_c/groups, *k] (IO...)
        kdims = w.shape[2:]
        pad_cfg = _resolve_padding(padding, nd, strides, dilations, kdims)
        if isinstance(pad_cfg, str):
            if pad_cfg == "SAME":
                pads = []
                for i in range(nd):
                    eff_k = (kdims[i] - 1) * dilations[i] + 1
                    total = eff_k - strides[i] if eff_k > strides[i] else 0
                    pads.append((total // 2, total - total // 2))
                pad_cfg = pads
            else:
                pad_cfg = [(0, 0)] * nd
        # grad-of-conv formulation: lax.conv_transpose handles fractional stride
        trans_pads = []
        for i in range(nd):
            lo, hi = pad_cfg[i]
            eff_k = (kdims[i] - 1) * dilations[i] + 1
            trans_pads.append((eff_k - 1 - lo, eff_k - 1 - hi + out_pad[i]))
        if groups > 1:
            # split channels; lax.conv_transpose has no feature_group_count
            in_per_g = v.shape[-1 if channel_last else 1] // groups
            outs = []
            for g in range(groups):
                if channel_last:
                    vg = v[..., g * in_per_g:(g + 1) * in_per_g]
                else:
                    vg = v[:, g * in_per_g:(g + 1) * in_per_g]
                wg = w[g * in_per_g:(g + 1) * in_per_g]
                outs.append(_one_transpose(vg, wg, strides, trans_pads, dilations, dn, channel_last, nd))
            out = jnp.concatenate(outs, axis=-1 if channel_last else 1)
        else:
            out = _one_transpose(v, w, strides, trans_pads, dilations, dn, channel_last, nd)
        if b:
            bias_shape = [1] * out.ndim
            bias_shape[-1 if channel_last else 1] = b[0].shape[0]
            out = out + b[0].reshape(bias_shape)
        return out

    args = (x, weight) + (() if bias is None else (bias,))
    return apply_op(f, *args, op_name=f"conv{nd}d_transpose")


def _one_transpose(v, w, strides, pads, dilations, dn, channel_last, nd):
    # Use input-dilated conv: insert (stride-1) zeros between input elements,
    # then convolve with the spatially-flipped kernel at stride 1.
    # w layout IO...: [in_c, out_c, *k] → conv kernel [out_c, in_c, *k] flipped.
    flip_axes = tuple(range(2, 2 + nd))
    w_conv = jnp.flip(jnp.swapaxes(w, 0, 1), flip_axes)  # OI...k flipped
    if channel_last:
        perm = tuple(range(2, 2 + nd)) + (1, 0)
        w_conv = jnp.transpose(w_conv, perm)
    return lax.conv_general_dilated(
        v, w_conv, window_strides=(1,) * nd, padding=pads,
        lhs_dilation=strides, rhs_dilation=dilations, dimension_numbers=dn,
    )


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, 1, fmt, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, 3, data_format, output_size)
