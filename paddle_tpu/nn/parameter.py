"""Parameter — a trainable Tensor.

Analog of the reference's ``EagerParamBase`` (python/paddle/fluid/framework.py)
/ ``phi::DenseTensor`` held by a Layer: a Tensor with ``stop_gradient=False``
by default plus optimizer metadata (lr multiplier, regularizer, clip flag).
"""
from __future__ import annotations

import jax

from ..core.tensor import Tensor

__all__ = ["Parameter"]

_param_counter = [0]


class Parameter(Tensor):
    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip",
                 "do_model_average", "is_distributed", "split_axis",
                 "pp_stage", "grad_pspec", "main_grad")

    def __init__(self, value, trainable: bool = True, name=None,
                 learning_rate: float = 1.0, regularizer=None,
                 need_clip: bool = True, do_model_average: bool = True):
        if name is None:
            name = f"param_{_param_counter[0]}"
            _param_counter[0] += 1
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": learning_rate}
        self.regularizer = regularizer
        self.need_clip = need_clip
        self.do_model_average = do_model_average
        self.persistable = True
        # distributed metadata (TP): which axis this param is split along, or
        # None if replicated (reference: param.is_distributed flag on mp layers)
        self.is_distributed = False
        self.split_axis = None
        # pipeline stage placement (None = not under a PipelineLayer)
        self.pp_stage = None
        # gradient placement (ZeRO-2: sharding-axis spec; None = follow param)
        self.grad_pspec = None

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


jax.tree_util.register_pytree_node(
    Parameter,
    lambda p: ((p._value,), (p.trainable, p.name)),
    lambda aux, children: Parameter(children[0], trainable=aux[0], name=aux[1]),
)
