"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capability surface, built on JAX/XLA/Pallas/pjit.

Top-level namespace mirrors ``paddle.*`` (reference: python/paddle/__init__.py)
so reference users find the same API shape; the execution model underneath is
traced XLA programs, not per-op kernel dispatch.
"""
from __future__ import annotations

import importlib

# jax compat: shard_map graduated out of jax.experimental after 0.4.x
# (renaming check_rep → check_vma on the way), and the codebase imports
# the graduated name (`from jax import shard_map`) with the graduated
# kwargs. Alias a translating wrapper on older jax so every internal
# module and user script sees one spelling.
import jax as _jax

if not hasattr(_jax, "shard_map"):
    try:
        from jax.experimental.shard_map import shard_map as _shard_map

        def _shard_map_compat(*args, **kwargs):
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            if "axis_names" in kwargs:
                # graduated API: axis_names = the axes shard_map manages;
                # experimental spelling: auto = the complement
                names = frozenset(kwargs.pop("axis_names"))
                mesh = kwargs.get("mesh",
                                  args[1] if len(args) > 1 else None)
                if mesh is not None:
                    auto = frozenset(mesh.axis_names) - names
                    if auto:
                        kwargs["auto"] = auto
            return _shard_map(*args, **kwargs)

        _jax.shard_map = _shard_map_compat
    except Exception:  # pragma: no cover - very old jax: leave unpatched
        pass

# dtypes
from .core.dtype import (
    bfloat16,
    bool_,
    complex64,
    complex128,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .core.place import (
    CPUPlace,
    CUDAPlace,
    TPUPlace,
    device_count,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    set_device,
)
from .core.random import get_rng_state, seed, set_rng_state
from .core.tensor import Tensor, is_tensor, to_tensor
from .core.autograd import enable_grad, no_grad, set_grad_enabled, is_grad_enabled

# functional op surface
from .ops import *  # noqa: F401,F403

__version__ = "0.1.0"

# Subpackages load lazily (PEP 562): paddle_tpu.nn, .optimizer, .distributed...
_LAZY_SUBMODULES = {
    "inference",
    "signal",
    "geometric",
    "audio",
    "text",
    "hub",
    "onnx",
    "cost_model",
    "device",
    "reader",
    "dataset",
    "amp",
    "autograd",
    "distributed",
    "distribution",
    "fft",
    "quantization",
    "framework",
    "hapi",
    "incubate",
    "io",
    "jit",
    "metric",
    "models",
    "monitor",
    "nn",
    "optimizer",
    "profiler",
    "regularizer",
    "serving",
    "sparse",
    "static",
    "utils",
    "vision",
}

_LAZY_ATTRS = {
    "grad": ("paddle_tpu.autograd", "grad"),
    "save": ("paddle_tpu.framework.io", "save"),
    "load": ("paddle_tpu.framework.io", "load"),
    "to_static": ("paddle_tpu.jit", "to_static"),
    "DataParallel": ("paddle_tpu.distributed.parallel", "DataParallel"),
    "Model": ("paddle_tpu.hapi.model", "Model"),
    "summary": ("paddle_tpu.hapi.model_summary", "summary"),
    "flops": ("paddle_tpu.hapi.dynamic_flops", "flops"),
    "ParamAttr": ("paddle_tpu.nn.param_attr", "ParamAttr"),
    "get_flags": ("paddle_tpu.framework.flags", "get_flags"),
    "set_flags": ("paddle_tpu.framework.flags", "set_flags"),
    "finfo": ("paddle_tpu.core.dtype", "finfo"),
    "dtype": ("paddle_tpu.framework.compat", "dtype"),
    "iinfo": ("paddle_tpu.core.dtype", "iinfo"),
    "bool": ("paddle_tpu.core.dtype", "bool_"),
    "CUDAPinnedPlace": ("paddle_tpu.core.place", "CUDAPinnedPlace"),
    "batch": ("paddle_tpu.framework.compat", "batch"),
    "LazyGuard": ("paddle_tpu.framework.compat", "LazyGuard"),
    "check_shape": ("paddle_tpu.framework.compat", "check_shape"),
    "disable_signal_handler": ("paddle_tpu.framework.compat",
                               "disable_signal_handler"),
    "set_printoptions": ("paddle_tpu.framework.compat", "set_printoptions"),
    "tolist": ("paddle_tpu.framework.compat", "tolist"),
    "get_cuda_rng_state": ("paddle_tpu.core.random", "get_rng_state"),
    "set_cuda_rng_state": ("paddle_tpu.core.random", "set_rng_state"),
    "pow_": ("paddle_tpu.framework.compat", "pow_"),
    "index_add_": ("paddle_tpu.framework.compat", "index_add_"),
    "index_put_": ("paddle_tpu.framework.compat", "index_put_"),
    "scatter_": ("paddle_tpu.framework.compat", "scatter_"),
    "squeeze_": ("paddle_tpu.framework.compat", "squeeze_"),
    "tanh_": ("paddle_tpu.framework.compat", "tanh_"),
    "unsqueeze_": ("paddle_tpu.framework.compat", "unsqueeze_"),
    "callbacks": ("paddle_tpu.hapi", "callbacks"),
    "synchronize": ("paddle_tpu.device", "synchronize"),
}


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name in _LAZY_ATTRS:
        mod_name, attr = _LAZY_ATTRS[name]
        obj = getattr(importlib.import_module(mod_name), attr)
        globals()[name] = obj
        return obj
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def __dir__():
    # PEP 562 lazy names are invisible to dir() unless listed here —
    # discoverability matters for API-surface parity checks and tooling
    return sorted(set(globals()) | _LAZY_SUBMODULES | set(_LAZY_ATTRS))


def enable_static():
    """Enter static (record-then-jit) mode — see paddle_tpu.static."""
    from .static import enable_static as _e

    return _e()


def disable_static(place=None):
    from .static import disable_static as _d

    return _d()


def in_dynamic_mode() -> bool:
    from .static.program import in_static_mode

    return not in_static_mode()
