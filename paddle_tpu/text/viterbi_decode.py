"""Viterbi decoding (reference: python/paddle/text/viterbi_decode.py +
phi viterbi_decode kernel). TPU-native: one lax.scan forward pass carrying
(alpha, backpointers), one reverse scan for the path — fully jittable,
static shapes, no per-step python.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from ..nn import Layer
from ..ops._helpers import unwrap

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _viterbi(potentials, transitions, lengths, include_bos_eos_tag):
    b, seq_len, n = potentials.shape
    lengths = lengths.astype(jnp.int32)
    if include_bos_eos_tag:
        # last tag = BOS, second-to-last = EOS (reference docstring)
        start_idx, stop_idx = n - 1, n - 2
        alpha = potentials[:, 0] + transitions[start_idx][None, :]
    else:
        alpha = potentials[:, 0]

    def step(carry, t):
        alpha = carry
        # scores[b, i, j] = alpha[b, i] + trans[i, j] + emit[b, t, j]
        scores = alpha[:, :, None] + transitions[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)            # [B, N]
        best_score = jnp.max(scores, axis=1) + potentials[:, t]
        live = (t < lengths)[:, None]
        new_alpha = jnp.where(live, best_score, alpha)
        bp = jnp.where(live, best_prev,
                       jnp.arange(n, dtype=best_prev.dtype)[None, :])
        return new_alpha, bp

    alpha, bps = jax.lax.scan(step, alpha, jnp.arange(1, seq_len))
    # bps: [seq_len-1, B, N]
    if include_bos_eos_tag:
        alpha = alpha + transitions[:, stop_idx][None, :]
    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1).astype(jnp.int32)   # [B]

    def back(carry, bp):
        # carry = tag at position j+1; bp[b, carry] = tag at position j,
        # which is what the reverse scan must EMIT for index j
        tag = carry
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        prev = prev.astype(jnp.int32)
        return prev, prev

    _, path_rev = jax.lax.scan(back, last_tag, bps, reverse=True)
    path = jnp.concatenate([path_rev, last_tag[None]], axis=0)  # [T, B]
    path = jnp.swapaxes(path, 0, 1)                              # [B, T]
    # zero-pad beyond each sequence's length; trim to max length
    tpos = jnp.arange(seq_len)[None, :]
    path = jnp.where(tpos < lengths[:, None], path, 0)
    max_len = jnp.max(lengths)
    return scores, path.astype(jnp.int64), max_len


_viterbi_jit = jax.jit(_viterbi, static_argnums=(3,))


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Highest-scoring tag sequence. potentials [B, L, N], transitions
    [N, N], lengths [B] → (scores [B], paths [B, max(lengths)])."""
    pot = unwrap(potentials)
    trans = unwrap(transition_params)
    lens = unwrap(lengths)
    scores, path, max_len = _viterbi_jit(pot, trans, lens,
                                         bool(include_bos_eos_tag))
    path = path[:, :int(max_len)]
    return Tensor(scores), Tensor(path)


class ViterbiDecoder(Layer):
    """Layer wrapper (reference viterbi_decode.py:95)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
