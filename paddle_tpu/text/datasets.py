"""Text datasets (reference: python/paddle/text/datasets/*.py).

Zero-egress build: the download step is gated. Each dataset accepts a
``data_file``/``data_dir`` pointing at a local copy in the published
layout; the parsing logic is real. Without local data a clear error says
what to fetch.
"""
from __future__ import annotations

import gzip
import os
import re
import tarfile
from typing import Optional

import numpy as np

from ..io import Dataset

__all__ = ["UCIHousing", "Imdb", "Imikolov", "Movielens", "Conll05st",
           "WMT14", "WMT16"]


def _require(path: Optional[str], name: str, hint: str) -> str:
    if path and os.path.exists(path):
        return path
    raise RuntimeError(
        f"{name}: no local data. This build has no network egress; fetch "
        f"{hint} on a connected machine and pass its local path.")


class UCIHousing(Dataset):
    """Boston housing regression (reference uci_housing.py). data_file:
    the whitespace-separated housing.data (506 rows x 14 cols)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train"):
        path = _require(data_file, "UCIHousing",
                        "https://archive.ics.uci.edu/ml/machine-learning-"
                        "databases/housing/housing.data")
        raw = np.loadtxt(path).astype(np.float32)
        # reference normalization: per-feature max/min/avg over full set
        maxs = raw.max(axis=0)
        mins = raw.min(axis=0)
        avgs = raw.mean(axis=0)
        feat = (raw[:, :-1] - avgs[:-1]) / (maxs[:-1] - mins[:-1])
        n_train = int(len(raw) * 0.8)
        if mode == "train":
            self.data = feat[:n_train]
            self.label = raw[:n_train, -1:]
        else:
            self.data = feat[n_train:]
            self.label = raw[n_train:, -1:]

    def __getitem__(self, idx):
        return self.data[idx], self.label[idx]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment (reference imdb.py). data_file: aclImdb_v1.tar.gz
    or an extracted aclImdb/ directory."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150):
        path = _require(data_file, "Imdb",
                        "https://ai.stanford.edu/~amaas/data/sentiment/"
                        "aclImdb_v1.tar.gz")
        self._tokenize = re.compile(r"\w+").findall
        docs, labels = [], []
        if os.path.isdir(path):
            texts = self._read_dir(path, mode)
        else:
            texts = self._read_tar(path, mode)
        self.word_idx = self._build_vocab(
            (self._tokenize(t.lower()) for t, _ in texts), cutoff)
        for text, lab in texts:
            toks = self._tokenize(text.lower())
            docs.append(np.array(
                [self.word_idx.get(w, self.word_idx["<unk>"])
                 for w in toks], np.int64))
            labels.append(lab)
        self.docs = docs
        self.labels = np.asarray(labels, np.int64)

    @staticmethod
    def _read_dir(root, mode):
        out = []
        for lab, sub in ((0, "pos"), (1, "neg")):
            d = os.path.join(root, mode, sub)
            for fn in sorted(os.listdir(d)):
                with open(os.path.join(d, fn), encoding="utf-8") as f:
                    out.append((f.read(), lab))
        return out

    @staticmethod
    def _read_tar(path, mode):
        out = []
        pats = {0: re.compile(rf"aclImdb/{mode}/pos/.*\.txt$"),
                1: re.compile(rf"aclImdb/{mode}/neg/.*\.txt$")}
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                for lab, pat in pats.items():
                    if pat.match(member.name):
                        out.append((
                            tf.extractfile(member).read().decode("utf-8"),
                            lab))
        return out

    @staticmethod
    def _build_vocab(token_iter, cutoff):
        freq = {}
        for toks in token_iter:
            for w in toks:
                freq[w] = freq.get(w, 0) + 1
        words = [w for w, c in sorted(freq.items(),
                                      key=lambda kv: (-kv[1], kv[0]))
                 if c > cutoff]
        idx = {w: i for i, w in enumerate(words)}
        idx["<unk>"] = len(idx)
        return idx

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB n-gram dataset (reference imikolov.py). data_file: the
    simple-examples.tgz archive or extracted ptb.{train,valid}.txt."""

    def __init__(self, data_file: Optional[str] = None, data_type="NGRAM",
                 window_size: int = 5, mode: str = "train",
                 min_word_freq: int = 50):
        path = _require(data_file, "Imikolov",
                        "http://www.fit.vutbr.cz/~imikolov/rnnlm/"
                        "simple-examples.tgz")
        which = "train" if mode == "train" else "valid"
        lines = self._read(path, which)
        train_lines = lines if which == "train" else self._read(path, "train")
        freq = {}
        for ln in train_lines:
            for w in ln.split():
                freq[w] = freq.get(w, 0) + 1
            # sentence boundary markers count once per line (reference
            # imikolov.py build_dict) so BOS/EOS get real vocab ids
            freq["<s>"] = freq.get("<s>", 0) + 1
            freq["<e>"] = freq.get("<e>", 0) + 1
        freq = {w: c for w, c in freq.items() if c >= min_word_freq}
        words = sorted(freq, key=lambda w: (-freq[w], w))
        self.word_idx = {w: i for i, w in enumerate(words)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.data = []
        for ln in lines:
            ids = [self.word_idx.get(w, unk) for w in ln.split()]
            ids = [self.word_idx.get("<s>", unk)] + ids \
                + [self.word_idx.get("<e>", unk)]
            if data_type.upper() == "NGRAM":
                for i in range(window_size, len(ids)):
                    self.data.append(np.asarray(ids[i - window_size:i + 1],
                                                np.int64))
            else:  # SEQ
                self.data.append((np.asarray(ids[:-1], np.int64),
                                  np.asarray(ids[1:], np.int64)))

    @staticmethod
    def _read(path, which):
        name = f"ptb.{which}.txt"
        if os.path.isdir(path):
            with open(os.path.join(path, name), encoding="utf-8") as f:
                return f.read().splitlines()
        with tarfile.open(path) as tf:
            member = [m for m in tf.getnames() if m.endswith(name)][0]
            return tf.extractfile(member).read().decode().splitlines()

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """MovieLens-1M ratings (reference movielens.py). data_file: ml-1m.zip
    or extracted ml-1m/ directory with ratings.dat/users.dat/movies.dat."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 test_ratio: float = 0.1, rand_seed: int = 0):
        path = _require(data_file, "Movielens",
                        "https://files.grouplens.org/datasets/movielens/"
                        "ml-1m.zip")
        import zipfile

        def read(name):
            if os.path.isdir(path):
                with open(os.path.join(path, name), encoding="latin1") as f:
                    return f.read().splitlines()
            with zipfile.ZipFile(path) as z:
                inner = [n for n in z.namelist() if n.endswith(name)][0]
                return z.read(inner).decode("latin1").splitlines()

        ratings = [ln.split("::") for ln in read("ratings.dat")]
        rng = np.random.RandomState(rand_seed)
        mask = rng.rand(len(ratings)) < test_ratio
        keep = mask if mode == "test" else ~mask
        self.data = [(int(u), int(m), float(r))
                     for (u, m, r, _), k in zip(ratings, keep) if k]

    def __getitem__(self, idx):
        u, m, r = self.data[idx]
        return (np.asarray(u, np.int64), np.asarray(m, np.int64),
                np.asarray(r, np.float32))

    def __len__(self):
        return len(self.data)


class _GatedDataset(Dataset):
    _NAME = ""
    _HINT = ""

    def __init__(self, data_file: Optional[str] = None, **kwargs):
        _require(data_file, self._NAME, self._HINT)
        raise NotImplementedError(
            f"{self._NAME} local parsing is not implemented in this build; "
            "the dataset requires its original preprocessing pipeline.")


class Conll05st(_GatedDataset):
    """CoNLL-2005 SRL (reference conll05.py) — gated (license-restricted
    download)."""
    _NAME = "Conll05st"
    _HINT = "the CoNLL-2005 shared-task distribution"


class WMT14(_GatedDataset):
    """WMT'14 en-fr (reference wmt14.py) — gated."""
    _NAME = "WMT14"
    _HINT = "the pre-tokenized WMT-14 archive"


class WMT16(_GatedDataset):
    """WMT'16 en-de (reference wmt16.py) — gated."""
    _NAME = "WMT16"
    _HINT = "the pre-tokenized WMT-16 archive"
