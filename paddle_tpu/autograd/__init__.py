"""User-facing autograd API (python/paddle/autograd/ parity)."""
from .functional import (backward, grad, hessian, jacobian,
                         saved_tensors_hooks)
from .py_layer import PyLayer, PyLayerContext
from ..core.autograd import no_grad, enable_grad, set_grad_enabled, is_grad_enabled

__all__ = [
    "backward",
    "grad",
    "jacobian",
    "hessian",
    "saved_tensors_hooks",
    "PyLayer",
    "PyLayerContext",
    "no_grad",
    "enable_grad",
    "set_grad_enabled",
    "is_grad_enabled",
]
