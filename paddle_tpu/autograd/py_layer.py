"""PyLayer: user-defined forward/backward pairs.

Reference: python/paddle/autograd/py_layer.py:29,255 + C++ side
fluid/eager/pylayer/. Here the custom backward plugs into the eager tape as a
GradNode whose pullback calls the user's ``backward`` staticmethod — the same
shape as ``jax.custom_vjp`` which we also expose for jitted paths.
"""
from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from ..core.autograd import GradNode, is_grad_enabled
from ..core.tensor import Tensor
from jax.tree_util import tree_flatten, tree_unflatten


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self._unpack_hook = None
        self.materialize_grads = True
        self._non_diff = set()

    def save_for_backward(self, *tensors):
        from ..core import autograd as _ag

        hooks = getattr(_ag, "_saved_tensor_hooks", None)
        if hooks is not None:
            tensors = tuple(hooks[0](t) for t in tensors)  # pack
        self._saved = tuple(tensors)
        # capture the UNPACK hook at save time: the canonical usage wraps
        # only the forward in the hooks context, and backward runs after
        # the context has exited
        self._unpack_hook = hooks[1] if hooks is not None else None

    def saved_tensor(self):
        """Returns the saved tuple — METHOD, matching paddle's documented
        ``ctx.saved_tensor()`` (python/paddle/autograd/py_layer.py).
        Unpacks through the hooks that were active at save time."""
        if self._unpack_hook is not None:
            return tuple(self._unpack_hook(t) for t in self._saved)
        return self._saved

    def saved_tensors(self):
        return self.saved_tensor()

    def mark_non_differentiable(self, *tensors):
        self._non_diff.update(id(t) for t in tensors)

    def set_materialize_grads(self, value: bool):
        self.materialize_grads = bool(value)


class PyLayerMeta(type):
    def __init__(cls, name, bases, attrs):
        super().__init__(name, bases, attrs)


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        outputs = cls.forward(ctx, *args, **kwargs)

        single = not isinstance(outputs, (list, tuple))
        out_list = [outputs] if single else list(outputs)

        tensor_inputs = [
            a for a in tree_flatten((args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))[0]
            if isinstance(a, Tensor)
        ]
        needs_grad = is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs
        )
        if not needs_grad:
            return outputs

        out_tensors = [o for o in out_list if isinstance(o, Tensor)]
        out_avals = [(tuple(o.shape), o.dtype) for o in out_tensors]

        in_avals = [(tuple(t.shape), t.dtype) for t in tensor_inputs]

        def vjp_fn(cotangents):
            cots = list(cotangents) if isinstance(cotangents, (list, tuple)) else [cotangents]
            grad_in = [Tensor(c, stop_gradient=True) for c in cots]
            res = cls.backward(ctx, *grad_in)
            if not isinstance(res, (list, tuple)):
                res = (res,)
            out = []
            for i, r in enumerate(res):
                if r is None:
                    shape, dt = in_avals[i] if i < len(in_avals) else ((), jnp.float32)
                    out.append(jnp.zeros(shape, dt))
                elif isinstance(r, Tensor):
                    out.append(r._value)
                else:
                    out.append(jnp.asarray(r))
            # pad missing slots with zeros for remaining inputs
            for i in range(len(out), len(in_avals)):
                shape, dt = in_avals[i]
                out.append(jnp.zeros(shape, dt))
            return tuple(out)

        import jax

        node = GradNode(
            vjp_fn,
            tensor_inputs,
            jax.tree_util.tree_structure(tuple(range(len(out_tensors)))),
            out_avals,
            name=cls.__name__,
        )
        for i, o in enumerate(out_tensors):
            o._node = node
            o._out_idx = i
            o.stop_gradient = False
        return outputs


# Alias matching paddle.autograd.PyLayerContext import path
LegacyPyLayer = PyLayer
