"""paddle.grad / paddle.autograd.backward parity
(reference: eager/backward.cc Backward + GeneralGrad at backward.cc:102)."""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

from ..core.autograd import run_backward
from ..core.tensor import Tensor


def _as_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def backward(tensors, grad_tensors=None, retain_graph=False):
    tensors = _as_list(tensors)
    grad_tensors = _as_list(grad_tensors) if grad_tensors is not None else None
    run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
):
    """paddle.grad parity. create_graph (higher-order through the eager tape)
    is not supported — use paddle_tpu.incubate.autograd functional transforms
    (jax.grad composition) for higher-order derivatives."""
    if create_graph:
        raise NotImplementedError(
            "create_graph=True: compose jax-level transforms via "
            "paddle_tpu.incubate.autograd instead"
        )
    outputs = _as_list(outputs)
    inputs = _as_list(inputs)
    grad_outputs = _as_list(grad_outputs) if grad_outputs is not None else None
    if retain_graph is None:
        retain_graph = False
    res = run_backward(
        outputs,
        grad_outputs,
        retain_graph=retain_graph,
        capture=inputs,
        accumulate_leaf_grads=False,
        allow_unused=allow_unused,
    )
    return res


def jacobian(func, xs, create_graph=False, batch_axis=None):
    """Jacobian of func at xs (reference autograd/functional.py jacobian /
    autograd.jacobian). TPU-native: jax.jacrev on the unwrapped arrays —
    one traced program, no per-row python loops."""
    import jax

    from ..core.tensor import Tensor

    single = not isinstance(xs, (list, tuple))
    xs_t = [xs] if single else list(xs)
    vals = [x.value if isinstance(x, Tensor) else x for x in xs_t]

    def f(*args):
        outs = func(*[Tensor(a) for a in args]) if single is False else \
            func(Tensor(args[0]))
        return outs.value if isinstance(outs, Tensor) else outs

    jac = jax.jacrev(f, argnums=tuple(range(len(vals))))(*vals)
    jac = [Tensor(j) for j in (jac if isinstance(jac, tuple) else (jac,))]
    return jac[0] if single else jac


def hessian(func, xs, create_graph=False, batch_axis=None):
    """Hessian of a scalar func at xs (reference autograd.hessian)."""
    import jax

    from ..core.tensor import Tensor

    single = not isinstance(xs, (list, tuple))
    xs_t = [xs] if single else list(xs)
    vals = [x.value if isinstance(x, Tensor) else x for x in xs_t]

    def f(*args):
        out = func(*[Tensor(a) for a in args])
        out = out.value if isinstance(out, Tensor) else out
        return out.reshape(())

    hes = jax.hessian(f, argnums=tuple(range(len(vals))))(*vals)
    if single:
        h = hes[0][0] if isinstance(hes, tuple) else hes
        return Tensor(h)
    return [[Tensor(hes[i][j]) for j in range(len(vals))]
            for i in range(len(vals))]


class saved_tensors_hooks:
    """Context manager installing pack/unpack hooks on autograd-saved
    tensors (reference autograd/saved_tensors_hooks.py). The eager tape
    consults these when stashing forward values for backward."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        from ..core import autograd as _ag

        self._prev = getattr(_ag, "_saved_tensor_hooks", None)
        _ag._saved_tensor_hooks = (self.pack_hook, self.unpack_hook)
        return self

    def __exit__(self, *exc):
        from ..core import autograd as _ag

        _ag._saved_tensor_hooks = self._prev
        return False
