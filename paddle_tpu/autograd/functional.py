"""paddle.grad / paddle.autograd.backward parity
(reference: eager/backward.cc Backward + GeneralGrad at backward.cc:102)."""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

from ..core.autograd import run_backward
from ..core.tensor import Tensor


def _as_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def backward(tensors, grad_tensors=None, retain_graph=False):
    tensors = _as_list(tensors)
    grad_tensors = _as_list(grad_tensors) if grad_tensors is not None else None
    run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
):
    """paddle.grad parity. create_graph (higher-order through the eager tape)
    is not supported — use paddle_tpu.incubate.autograd functional transforms
    (jax.grad composition) for higher-order derivatives."""
    if create_graph:
        raise NotImplementedError(
            "create_graph=True: compose jax-level transforms via "
            "paddle_tpu.incubate.autograd instead"
        )
    outputs = _as_list(outputs)
    inputs = _as_list(inputs)
    grad_outputs = _as_list(grad_outputs) if grad_outputs is not None else None
    if retain_graph is None:
        retain_graph = False
    res = run_backward(
        outputs,
        grad_outputs,
        retain_graph=retain_graph,
        capture=inputs,
        accumulate_leaf_grads=False,
        allow_unused=allow_unused,
    )
    return res
