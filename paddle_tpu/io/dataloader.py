"""DataLoader (python/paddle/io/dataloader/dataloader_iter.py parity).

The reference forks multiprocess workers feeding shared-memory tensors into
a C++ blocking queue (_DataLoaderIterMultiProcess dataloader_iter.py:358,
_worker_loop :451, out-of-order reorder :700). TPU-native design: the input
pipeline's job is to keep the host→HBM transfer ahead of the step. With
``num_workers>0`` samples are produced by FORKED WORKER PROCESSES — Python-
heavy transforms (the vision pipeline) run truly in parallel, not
GIL-serialized — results ride a pickle-over-pipe queue and are re-ordered
by batch index in the parent. ``use_shared_memory=False`` falls back to the
thread pool (fine for numpy-decode datasets that release the GIL; also the
path for unpicklable datasets). Batches stay as stacked numpy arrays —
the jit boundary does the single host→device transfer.
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, List, Optional

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "get_worker_info"]

_worker_info = threading.local()


class WorkerInfo:
    def __init__(self, id_, num_workers, dataset):
        self.id = id_
        self.num_workers = num_workers
        self.dataset = dataset


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch: List[Any]):
    """Stack samples into batched Tensors (reference:
    python/paddle/io/dataloader/collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor(jnp.stack([s.value for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.floating, np.integer)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(f)) for f in transposed)
    raise TypeError(f"batch data cannot be a {type(sample)}")


def _fetch(dataset, indices, collate_fn):
    return collate_fn([dataset[i] for i in indices])


def _np_collate(batch: List[Any]):
    """default_collate in numpy form — what worker PROCESSES produce.
    Device arrays must not be created in forked children (each would boot
    its own backend); the parent wraps the numpy tree into Tensors."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        for s in batch:
            v = s.value
            devs = getattr(v, "devices", None)
            if devs and any(d.platform != "cpu" for d in devs()):
                raise RuntimeError(
                    "DataLoader worker process received an accelerator-"
                    "backed Tensor from __getitem__; device transfers "
                    "inside forked workers hang. Return numpy arrays from "
                    "the dataset, or use use_shared_memory=False (thread "
                    "workers).")
        return np.stack([np.asarray(s.value) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.floating, np.integer)):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: _np_collate([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(_np_collate(list(f)) for f in transposed)
    raise TypeError(f"batch data cannot be a {type(sample)}")


def _tensorize(tree):
    """Wrap a numpy collate tree into Tensors (parent-side, zero-copy)."""
    if isinstance(tree, np.ndarray):
        return Tensor(tree)
    if isinstance(tree, dict):
        return {k: _tensorize(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)) and tree and not isinstance(
            tree[0], (str, bytes)):
        return type(tree)(_tensorize(v) for v in tree)
    return tree


def _worker_loop(dataset, index_q, result_q, collate_fn, init_fn, wid,
                 num_workers):
    """Reference _worker_loop (dataloader_iter.py:451): pull index batches,
    fetch + collate, push pre-pickled (batch_idx, result) — errors travel
    as strings. Results are serialized HERE (mp.Queue pickles in a feeder
    thread, where an unpicklable result would be silently dropped and the
    parent would wait forever; pickling in the try block turns that into a
    propagated error instead)."""
    import pickle
    import traceback

    def _err(bidx, e):
        result_q.put(pickle.dumps(
            (bidx, None, f"{type(e).__name__}: {e}\n"
                         f"{traceback.format_exc()}")))

    _worker_info.info = WorkerInfo(wid, num_workers, dataset)
    try:
        if init_fn:
            init_fn(wid)
    except Exception as e:  # a failed init must not go unnoticed: wrong
        _err(None, e)       # seeding/shard would silently corrupt training
        return
    while True:
        item = index_q.get()
        if item is None:
            break
        bidx, indices = item
        try:
            result_q.put(pickle.dumps(
                (bidx, _fetch(dataset, indices, collate_fn), None)))
        except Exception as e:  # propagate to parent, keep worker alive
            _err(bidx, e)


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        from .. import monitor

        # loader label: concurrent DataLoaders must not clobber one
        # shared queue-depth gauge (same reason the KV gauges carry a
        # pool label); the series is retired when iteration ends
        self._monitor_id = monitor.instance_label("loader")
        self.dataset = dataset
        self.num_workers = max(0, int(num_workers))
        self.use_shared_memory = bool(use_shared_memory)
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("length of IterableDataset loader is unknown")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def __iter__(self):
        if self._iterable_mode:
            return self._instrument(self._iter_iterable())
        if self.batch_sampler is None:
            # batch_size=None → sample-by-sample passthrough
            return self._instrument(
                self.collate_fn([self.dataset[i]])
                for i in range(len(self.dataset)))
        if self.num_workers == 0:
            return self._instrument(self._iter_single())
        if self.use_shared_memory:
            return self._instrument(self._iter_processes())
        return self._instrument(self._iter_workers())

    @staticmethod
    def _depth_metric():
        """The ONE declaration of the queue-depth gauge (bind and retire
        must target the same registration)."""
        from .. import monitor

        return monitor.gauge(
            "paddle_tpu_dataloader_queue_depth",
            "prefetched batches in flight (producer lead over the "
            "consumer) per live loader", ("loader",))

    def _depth_gauge(self):
        """Per-loader bound queue-depth gauge, or None when the monitor
        is off."""
        from .. import monitor

        if not monitor.enabled():
            return None
        return self._depth_metric().labels(loader=self._monitor_id)

    def _retire_depth_gauge(self, depth):
        """Drop this loader's depth series when iteration ends so dead
        loaders don't export a stale depth forever."""
        if depth is None:
            return
        try:
            self._depth_metric().remove(loader=self._monitor_id)
        except Exception:
            pass

    def _instrument(self, it):
        """Monitor shim: time spent blocked in ``next()`` is exactly the
        step's input-starvation time (host work between batches is the
        caller's). Off-monitor cost: one enabled() check per epoch."""
        from .. import monitor

        if not monitor.enabled():
            return it
        wait = monitor.histogram(
            "paddle_tpu_dataloader_wait_seconds",
            "time the consumer blocked waiting for the next batch "
            "(input-pipeline starvation)")
        batches = monitor.counter(
            "paddle_tpu_dataloader_batches_total",
            "batches delivered by DataLoader iterators")

        def gen():
            while True:
                t0 = time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    return
                wait.observe(time.perf_counter() - t0)
                batches.inc()
                yield batch

        return gen()

    def _iter_single(self):
        for indices in self.batch_sampler:
            yield _fetch(self.dataset, indices, self.collate_fn)

    def _iter_iterable(self):
        it = iter(self.dataset)
        while True:
            batch = list(itertools.islice(it, self.batch_size))
            if not batch:
                return
            if len(batch) < self.batch_size and self.drop_last:
                return
            yield self.collate_fn(batch)

    def _iter_workers(self):
        """Bounded-prefetch pipeline: worker threads run dataset.__getitem__
        + collate in parallel (numpy decode releases the GIL), results are
        delivered in order (≙ reference _DataLoaderIterMultiProcess out-of-
        order reorder buffer, dataloader_iter.py:700)."""
        max_inflight = self.num_workers * self.prefetch_factor
        pool = ThreadPoolExecutor(max_workers=self.num_workers)
        if self.worker_init_fn:
            for i in range(self.num_workers):
                pool.submit(self.worker_init_fn, i)
        indices_iter = iter(self.batch_sampler)
        futures: "queue.Queue" = queue.Queue()
        stop = threading.Event()

        def submitter():
            for indices in indices_iter:
                if stop.is_set():
                    break
                while futures.qsize() >= max_inflight and not stop.is_set():
                    stop.wait(0.001)
                futures.put(pool.submit(_fetch, self.dataset, indices,
                                        self.collate_fn))
            futures.put(None)

        depth = self._depth_gauge()
        t = threading.Thread(target=submitter, daemon=True)
        t.start()
        try:
            while True:
                if depth is not None:
                    depth.set(futures.qsize())
                fut = futures.get()
                if fut is None:
                    return
                yield fut.result()
        finally:
            stop.set()
            pool.shutdown(wait=False, cancel_futures=True)
            self._retire_depth_gauge(depth)

    def _start_method(self) -> str:
        """fork is cheapest, but forking after the JAX backend has live
        threads+locks in the parent (the typical case — the model is built
        before iteration) can deadlock children on cloned locked mutexes.
        Prefer forkserver then, provided dataset/collate/init_fn survive
        pickling (forkserver children start fresh, nothing is cloned).
        Unpicklable datasets keep fork; ``use_shared_memory=False``
        (thread pool) is the fully-safe fallback."""
        cached = getattr(self, "_start_method_cache", None)
        if cached is not None:
            return cached
        import os
        import sys

        try:
            from jax._src import xla_bridge

            jax_up = bool(getattr(xla_bridge, "_backends", None))
        except Exception:
            # probe broke (private attr moved): fail toward the SAFE mode
            # whenever jax is even imported — fork is the deadlock risk
            jax_up = "jax" in sys.modules
        if not jax_up:
            return "fork"  # liveness can transition up: don't cache
        # forkserver children re-import __main__ (spawn.prepare); that
        # requires __main__ to actually be importable — a stdin/REPL/
        # notebook session has no real file and the child would die in
        # runpy. fork is the only working mode there.
        main = sys.modules.get("__main__")
        spec = getattr(main, "__spec__", None)
        mfile = getattr(main, "__file__", None)
        if spec is None and not (mfile and os.path.exists(mfile)):
            method = "fork"
        else:
            try:
                import pickle

                pickle.dumps((self.dataset, self.collate_fn,
                              self.worker_init_fn))
                method = "forkserver"
            except Exception:
                method = "fork"
        # jax-up is permanent; cache so epochs>1 skip the dataset pickle
        self._start_method_cache = method
        return method

    def _iter_processes(self):
        """Forked worker processes + ordered delivery (the reference
        multiprocess path, dataloader_iter.py:358). Index batches fan out
        over one shared queue; results come back (batch_idx, data, err) and
        a reorder buffer restores sampler order (reference :700)."""
        method = self._start_method()
        ctx = mp.get_context(method)
        if method == "forkserver":
            # forkserver preloads __main__ by default, which would re-run
            # unguarded user training scripts inside the server process
            ctx.set_forkserver_preload([])
        index_q = ctx.Queue()
        result_q = ctx.Queue()
        # default collate runs in numpy form inside workers; custom
        # collate_fns run as-is (reference semantics — user's code runs in
        # the worker process)
        wl_collate = (_np_collate if self.collate_fn is default_collate_fn
                      else self.collate_fn)
        workers = [
            ctx.Process(
                target=_worker_loop,
                args=(self.dataset, index_q, result_q, wl_collate,
                      self.worker_init_fn, i, self.num_workers),
                daemon=True)
            for i in range(self.num_workers)
        ]
        for w in workers:
            w.start()
        max_inflight = self.num_workers * self.prefetch_factor
        indices_iter = enumerate(iter(self.batch_sampler))
        sent = 0
        done_sending = False

        def send_one():
            nonlocal sent, done_sending
            try:
                bidx, indices = next(indices_iter)
            except StopIteration:
                done_sending = True
                return
            index_q.put((bidx, list(indices)))
            sent += 1

        depth = self._depth_gauge()
        try:
            for _ in range(max_inflight):
                if done_sending:
                    break
                send_one()
            reorder = {}
            nxt = 0
            while nxt < sent or not done_sending:
                if depth is not None:
                    depth.set(sent - nxt)
                if nxt in reorder:
                    data, err = reorder.pop(nxt)
                else:
                    try:
                        import pickle

                        bidx, data, err = pickle.loads(result_q.get(
                            timeout=self.timeout or 5.0))
                    except queue.Empty:
                        if self.timeout:
                            raise RuntimeError(
                                f"DataLoader worker timed out after "
                                f"{self.timeout}s (batch {nxt})")
                        # no user timeout: periodic liveness poll — a
                        # worker killed mid-batch would deadlock the get
                        if not all(w.is_alive() for w in workers):
                            raise RuntimeError(
                                "DataLoader worker died unexpectedly "
                                "(killed / segfault); restart the loader")
                        continue
                    if bidx is None:   # worker_init_fn failure
                        raise RuntimeError(
                            f"DataLoader worker_init_fn raised:\n{err}")
                    if bidx != nxt:
                        reorder[bidx] = (data, err)
                        continue
                if err is not None:
                    raise RuntimeError(
                        f"DataLoader worker raised:\n{err}")
                if not done_sending:
                    send_one()
                if wl_collate is _np_collate:
                    data = _tensorize(data)
                yield data
                nxt += 1
        finally:
            for _ in workers:
                index_q.put(None)
            for w in workers:
                w.join(timeout=2)
                if w.is_alive():
                    w.terminate()
            for q_ in (index_q, result_q):
                q_.cancel_join_thread()
                q_.close()
            self._retire_depth_gauge(depth)

    def __call__(self):
        return self.__iter__()
