"""DataLoader (python/paddle/io/dataloader/dataloader_iter.py parity).

The reference forks multiprocess workers feeding shared-memory tensors into a
C++ blocking queue (_DataLoaderIterMultiProcess, dataloader_iter.py:358).
TPU-native design: the input pipeline's job is to keep the host→HBM transfer
ahead of the step; workers here are a process pool (true parallel decode for
numpy-producing datasets) with a bounded prefetch queue, and batches stay as
stacked numpy arrays — jit boundaries do the single host→device transfer.
"""
from __future__ import annotations

import itertools
import queue
import threading
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from typing import Any, Callable, List, Optional

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "get_worker_info"]

_worker_info = threading.local()


class WorkerInfo:
    def __init__(self, id_, num_workers, dataset):
        self.id = id_
        self.num_workers = num_workers
        self.dataset = dataset


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch: List[Any]):
    """Stack samples into batched Tensors (reference:
    python/paddle/io/dataloader/collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor(jnp.stack([s.value for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.floating, np.integer)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(f)) for f in transposed)
    raise TypeError(f"batch data cannot be a {type(sample)}")


def _fetch(dataset, indices, collate_fn):
    return collate_fn([dataset[i] for i in indices])


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.num_workers = max(0, int(num_workers))
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("length of IterableDataset loader is unknown")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.batch_sampler is None:
            # batch_size=None → sample-by-sample passthrough
            return (self.collate_fn([self.dataset[i]])
                    for i in range(len(self.dataset)))
        if self.num_workers == 0:
            return self._iter_single()
        return self._iter_workers()

    def _iter_single(self):
        for indices in self.batch_sampler:
            yield _fetch(self.dataset, indices, self.collate_fn)

    def _iter_iterable(self):
        it = iter(self.dataset)
        while True:
            batch = list(itertools.islice(it, self.batch_size))
            if not batch:
                return
            if len(batch) < self.batch_size and self.drop_last:
                return
            yield self.collate_fn(batch)

    def _iter_workers(self):
        """Bounded-prefetch pipeline: worker threads run dataset.__getitem__
        + collate in parallel (numpy decode releases the GIL), results are
        delivered in order (≙ reference _DataLoaderIterMultiProcess out-of-
        order reorder buffer, dataloader_iter.py:700)."""
        max_inflight = self.num_workers * self.prefetch_factor
        pool = ThreadPoolExecutor(max_workers=self.num_workers)
        if self.worker_init_fn:
            for i in range(self.num_workers):
                pool.submit(self.worker_init_fn, i)
        indices_iter = iter(self.batch_sampler)
        futures: "queue.Queue" = queue.Queue()
        stop = threading.Event()

        def submitter():
            for indices in indices_iter:
                if stop.is_set():
                    break
                while futures.qsize() >= max_inflight and not stop.is_set():
                    stop.wait(0.001)
                futures.put(pool.submit(_fetch, self.dataset, indices,
                                        self.collate_fn))
            futures.put(None)

        t = threading.Thread(target=submitter, daemon=True)
        t.start()
        try:
            while True:
                fut = futures.get()
                if fut is None:
                    return
                yield fut.result()
        finally:
            stop.set()
            pool.shutdown(wait=False, cancel_futures=True)

    def __call__(self):
        return self.__iter__()
