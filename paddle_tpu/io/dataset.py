"""Datasets (python/paddle/io/dataset.py parity)."""
from __future__ import annotations

import bisect
from typing import Iterable, List, Sequence

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset does not support len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        lens = {t.shape[0] for t in tensors}
        if len(lens) != 1:
            raise ValueError("all tensors must share dim-0 size")
        self.tensors = list(tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        lens = {len(d) for d in self.datasets}
        if len(lens) != 1:
            raise ValueError("all datasets must have the same length")

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)

    def __len__(self):
        return len(self.datasets[0])


class ChainDataset(IterableDataset):
    def __init__(self, datasets: Sequence[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = 0 if ds_idx == 0 else self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence, generator=None):
    total = len(dataset)
    lengths = list(lengths)
    if all(isinstance(l, float) and 0 <= l <= 1 for l in lengths):
        sizes = [int(np.floor(total * l)) for l in lengths]
        for i in range(total - sum(sizes)):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    if sum(lengths) != total:
        raise ValueError("sum of input lengths != dataset length")
    perm = np.random.permutation(total).tolist()
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l]))
        offset += l
    return out
