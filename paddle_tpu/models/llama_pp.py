"""Llama hybrid-parallel training step: pp(stage-local) x tp x dp x ZeRO.

This is BASELINE config 3's shape (Llama hybrid TP x PP x sharding) built
the TPU way: ONE jitted program where

- **pp** is manual — `pp_sharded.build_sharded_1f1b_grad_fn` runs true 1F1B
  under `shard_map` with stage-LOCAL stacked params (each device holds 1/S
  of the decoder body, its grads and its optimizer state);
- **tp** is Megatron column/row placement expressed as NamedSharding on the
  feature dims of the stacked weights (q/k/v/gate/up column-split, o/down
  row-split, vocab-parallel embedding) — GSPMD inserts the psums the
  reference codes by hand in mp_ops (fleet/layers/mpu/mp_layers.py:173,343);
- **dp** is batch sharding on the microbatch dim;
- **ZeRO** is optimizer-state placement: AdamW moments carry an extra
  `sharding`-axis annotation, so XLA reduce-scatters grads into the update
  and all-gathers fresh params — the stage-1/2 semantics of
  DygraphShardingOptimizer (dygraph_sharding_optimizer.py:94) without a
  hand-written partitioner.

Reference analog for the composition switch: fleet/model.py:134-170.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from .llama import LlamaConfig, _rope_cos_sin, apply_rotary_emb
from .llama_functional import _layer_fwd, _rms

__all__ = ["llama_pp_fns", "block_specs", "edge_specs", "moment_specs",
           "build_llama_hybrid_step", "hybrid_memory_analysis",
           "save_hybrid_checkpoint", "load_hybrid_checkpoint"]


def llama_pp_fns(cfg: LlamaConfig, remat: bool = True,
                 ignore_index: int = -100):
    """(first_fn, body_fn, last_fn) for pp_sharded over the
    llama_functional stacked-param naming."""

    def first_fn(edge, ids):
        return jnp.take(edge["model.embed_tokens.weight"], ids, axis=0)

    def body_fn(chunk, h):
        cos, sin = _rope_cos_sin(h.shape[1], cfg.head_dim, cfg.rope_theta,
                                 h.dtype)

        def body(x, lp):
            return _layer_fwd(lp, x, cos, sin, cfg), None

        if remat:
            body = jax.checkpoint(body)
        h, _ = jax.lax.scan(body, h, chunk)
        return h

    def last_fn(edge, h, labels):
        x = _rms(h, edge["model.norm.weight"], cfg.rms_norm_eps)
        w = edge.get("lm_head.weight")
        logits = (x @ w if w is not None
                  else x @ edge["model.embed_tokens.weight"].T)
        lbl = jnp.clip(labels, 0, cfg.vocab_size - 1)
        lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        tgt = jnp.take_along_axis(logits, lbl[..., None], -1)[..., 0]
        nll = lse - tgt.astype(jnp.float32)
        mask = (labels != ignore_index).astype(jnp.float32)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    return first_fn, body_fn, last_fn


# Megatron placement per stacked-leaf name. Blocks have leading dims
# (S, V, lpc); dim 3 is the input-feature dim, dim 4 (when present) the
# output-feature dim. Column-parallel = split the output features over mp;
# row-parallel = split the input features (reference mp_layers.py:173,343).
_COL = ("self_attn.q_proj.weight", "self_attn.k_proj.weight",
        "self_attn.v_proj.weight", "mlp.gate_proj.weight",
        "mlp.up_proj.weight")
_ROW = ("self_attn.o_proj.weight", "mlp.down_proj.weight")


def block_specs(stacked_keys, zero: bool = False) -> Dict[str, P]:
    """PartitionSpecs for pp blocks. With ``zero`` the non-mp feature dim is
    additionally split over the ``sharding`` axis (used for moments)."""
    z = "sharding" if zero else None
    specs = {}
    for k in stacked_keys:
        if k in _COL:
            specs[k] = P("pp", None, None, z, "mp")
        elif k in _ROW:
            specs[k] = P("pp", None, None, "mp", z)
        else:  # 1-D per-layer vectors (norm weights)
            specs[k] = P("pp", None, None, z)
    return specs


def edge_specs(rest_keys, zero: bool = False) -> Dict[str, P]:
    """Vocab-parallel embedding + column-parallel head; final norm
    replicated. (mp_layers.py:35 VocabParallelEmbedding.)"""
    z = "sharding" if zero else None
    specs = {}
    for k in rest_keys:
        if k == "model.embed_tokens.weight":
            specs[k] = P("mp", z)
        elif k == "lm_head.weight":
            specs[k] = P(z, "mp")
        else:
            specs[k] = P(z)
    return specs


def moment_specs(blocks, rest) -> Tuple[Dict[str, P], Dict[str, P]]:
    """ZeRO placement for AdamW moments: same mp split as the params plus a
    sharding-axis split on the other feature dim."""
    return (block_specs(blocks.keys(), zero=True),
            edge_specs(rest.keys(), zero=True))


def _shard(tree, specs, mesh):
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in tree.items()}


def build_llama_hybrid_step(cfg: LlamaConfig, mesh: Mesh,
                            accumulate_steps: int,
                            num_virtual_stages: int = 1,
                            lr: float = 1e-4, clip_norm: float = 1.0,
                            zero: bool = True, remat: bool = True,
                            moment_dtype=jnp.float32,
                            stash: Optional[str] = None,
                            zero_stage: int = 2):
    """Returns ``(step, prepare)``:

    - ``prepare(stacked, rest) -> (blocks, edge, opt_state)`` — rearranges
      layer-stacked params into pp blocks, places every tensor according to
      the hybrid specs, builds sharded AdamW state.
    - ``step(blocks, edge, opt_state, ids, labels) ->
      (blocks, edge, opt_state, loss)`` — jitted 1F1B hybrid train step
      with donated buffers.

    ``stash`` picks the 1F1B activation policy:

    - ``"residuals"``: hand-split decoder backward over stashed per-layer
      residuals — each decoder forward runs ONCE (~ideal FLOPs; the
      reference's stored-activation 1F1B, pipeline_parallel.py:372/677) at
      the cost of ~2S in-flight microbatches of full layer activations.
      ``remat`` is moot on this path (nothing is recomputed).
    - ``"input"``: stash only stage-boundary activations and re-run the
      chunk forward inside the backward tick's ``jax.vjp`` (~1.33x FLOPs)
      — the full-recompute choice for memory-bound scales.
    - ``None`` (default): follow ``remat`` — a caller asking for remat
      wants the memory-lean profile (``"input"``); ``remat=False`` gets
      the fast path (``"residuals"``). Existing callers keep their
      memory behavior; pass ``stash`` explicitly to decouple.
    """
    from ..distributed.fleet.meta_parallel.pp_sharded import (
        blocks_from_stacked, build_sharded_1f1b_grad_fn,
        build_sharded_1f1b_resid_grad_fn)
    from ..optimizer.functional import (adamw_init, adamw_update,
                                        clip_by_global_norm)

    S = int(mesh.shape.get("pp", 1))
    V = int(num_virtual_stages)
    first_fn, body_fn, last_fn = llama_pp_fns(cfg, remat=remat)
    if stash is None:
        stash = "input" if remat not in (False, "none") else "residuals"
    if stash == "residuals":
        from .llama_residual import make_body_fwd_bwd

        body_fwd, body_bwd = make_body_fwd_bwd(cfg)
        grad_fn = build_sharded_1f1b_resid_grad_fn(
            first_fn, body_fwd, body_bwd, last_fn, accumulate_steps, mesh,
            num_virtual_stages=V)
    elif stash == "input":
        grad_fn = build_sharded_1f1b_grad_fn(
            first_fn, body_fn, last_fn, accumulate_steps, mesh,
            num_virtual_stages=V)
    else:
        raise ValueError(f"unknown stash policy {stash!r}")

    def prepare(stacked, rest):
        blocks = blocks_from_stacked(stacked, S, V)
        # ZeRO stage 3: PARAMS are sharded at rest too (the non-mp
        # feature dim rides the `sharding` axis; GSPMD all-gathers at
        # use) — the DygraphShardingOptimizer stage-3 placement,
        # BASELINE config 3's "sharding-stage-3"
        stage3 = zero and zero_stage >= 3
        bspec = block_specs(blocks.keys(), zero=stage3)
        espec = edge_specs(rest.keys(), zero=stage3)
        blocks = _shard(blocks, bspec, mesh)
        edge = _shard(rest, espec, mesh)
        st = adamw_init({"b": blocks, "e": edge}, master_dtype=moment_dtype)
        if zero:
            mb, me = moment_specs(blocks, rest)
            st = st._replace(
                m={"b": _shard(st.m["b"], mb, mesh),
                   "e": _shard(st.m["e"], me, mesh)},
                v={"b": _shard(st.v["b"], mb, mesh),
                   "e": _shard(st.v["e"], me, mesh)})
        return blocks, edge, st

    def step(blocks, edge, opt_state, ids, labels):
        loss, (gb, ge) = grad_fn(blocks, edge, ids, labels)
        grads = {"b": gb, "e": ge}
        if clip_norm:
            grads, _ = clip_by_global_norm(grads, clip_norm)
        opt_state, params = adamw_update(
            grads, opt_state, {"b": blocks, "e": edge}, lr=lr,
            master_dtype=moment_dtype)
        return params["b"], params["e"], opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1, 2)), prepare


def llama_param_shapes(cfg: LlamaConfig):
    """(stacked_shapes, rest_shapes) of the llama_functional layout, from
    the config alone — lets compile-only analysis at 13B/65B dims build
    abstract arguments without materializing half a terabyte of params."""
    L, H, I = (cfg.num_hidden_layers, cfg.hidden_size,
               cfg.intermediate_size)
    nh, kvh, hd = (cfg.num_attention_heads, cfg.kv_heads, cfg.head_dim)
    stacked = {
        "input_layernorm.weight": (L, H),
        "post_attention_layernorm.weight": (L, H),
        "self_attn.q_proj.weight": (L, H, nh * hd),
        "self_attn.k_proj.weight": (L, H, kvh * hd),
        "self_attn.v_proj.weight": (L, H, kvh * hd),
        "self_attn.o_proj.weight": (L, nh * hd, H),
        "mlp.gate_proj.weight": (L, H, I),
        "mlp.up_proj.weight": (L, H, I),
        "mlp.down_proj.weight": (L, I, H),
    }
    rest = {
        "model.embed_tokens.weight": (cfg.vocab_size, H),
        "model.norm.weight": (H,),
        "lm_head.weight": (H, cfg.vocab_size),
    }
    return stacked, rest


def hybrid_memory_analysis(cfg: LlamaConfig, mesh: Mesh,
                           accumulate_steps: int,
                           num_virtual_stages: int = 1,
                           batch_per_micro: int = 1, seq_len: int = 4096,
                           zero: bool = True, remat=True,
                           stash: Optional[str] = None,
                           param_dtype=jnp.bfloat16,
                           moment_dtype=jnp.float32,
                           hbm_budget: int = 95 << 30,
                           zero_stage: int = 2) -> Dict[str, Any]:
    """Compile-only per-device memory feasibility for BASELINE config 3
    (Llama-2 13B/65B hybrid TP x PP x sharding) — proves the stage-local
    PP + ZeRO placement fits a v5p HBM budget WITHOUT the hardware.

    Builds the full jitted hybrid train step at real dims over abstract
    sharded arguments (``jax.ShapeDtypeStruct`` + NamedSharding — nothing
    is materialized), compiles it AOT, and reads XLA's buffer-assignment
    ``memory_analysis()``. Returns a report dict; ``fits`` is the headline
    (per-device arguments + temps within ``hbm_budget``; with donation the
    outputs alias the argument buffers).

    Run via ``python bench.py hybrid`` (spawns the virtual-device mesh) or
    the 13B/8-device test in tests/test_hybrid_memory.py.
    """
    import functools

    from ..distributed.fleet.meta_parallel.pp_sharded import (
        blocks_from_stacked)
    from ..optimizer.functional import adamw_init

    S = int(mesh.shape.get("pp", 1))
    V = int(num_virtual_stages)
    M = int(accumulate_steps)
    # resolve the stash default ONCE (same rule as build_llama_hybrid_step)
    # so the report names the policy that was actually compiled
    if stash is None:
        stash = "input" if remat not in (False, "none") else "residuals"
    stacked_shapes, rest_shapes = llama_param_shapes(cfg)
    stacked_avals = {k: jax.ShapeDtypeStruct(s, param_dtype)
                     for k, s in stacked_shapes.items()}
    rest_avals = {k: jax.ShapeDtypeStruct(s, param_dtype)
                  for k, s in rest_shapes.items()}
    blocks_avals = jax.eval_shape(
        functools.partial(blocks_from_stacked, S=S, V=V), stacked_avals)

    def _sds(avals, specs):
        return {k: jax.ShapeDtypeStruct(
                    v.shape, v.dtype,
                    sharding=NamedSharding(mesh, specs[k]))
                for k, v in avals.items()}

    stage3 = zero and zero_stage >= 3
    bspec = block_specs(blocks_avals.keys(), zero=stage3)
    espec = edge_specs(rest_avals.keys(), zero=stage3)
    blocks_in = _sds(blocks_avals, bspec)
    edge_in = _sds(rest_avals, espec)
    opt_aval = jax.eval_shape(
        lambda b, e: adamw_init({"b": b, "e": e},
                                master_dtype=moment_dtype),
        blocks_avals, rest_avals)
    if zero:
        mb, me = moment_specs(blocks_avals, rest_avals)
    else:
        mb, me = bspec, espec
    rep = NamedSharding(mesh, P())
    opt_in = opt_aval._replace(
        step=jax.ShapeDtypeStruct(opt_aval.step.shape, opt_aval.step.dtype,
                                  sharding=rep),
        m={"b": _sds(opt_aval.m["b"], mb), "e": _sds(opt_aval.m["e"], me)},
        v={"b": _sds(opt_aval.v["b"], mb), "e": _sds(opt_aval.v["e"], me)})
    gb = M * batch_per_micro
    ids_in = jax.ShapeDtypeStruct((gb, seq_len), jnp.int32, sharding=rep)
    y_in = jax.ShapeDtypeStruct((gb, seq_len), jnp.int32, sharding=rep)

    step, _ = build_llama_hybrid_step(
        cfg, mesh, accumulate_steps=M, num_virtual_stages=V,
        zero=zero, remat=remat, stash=stash, moment_dtype=moment_dtype,
        zero_stage=zero_stage)
    compiled = step.lower(blocks_in, edge_in, opt_in, ids_in, y_in).compile()
    ma = compiled.memory_analysis()
    arg_b = int(ma.argument_size_in_bytes)
    tmp_b = int(ma.temp_size_in_bytes)
    out_b = int(ma.output_size_in_bytes)
    # donated params/opt-state alias their outputs; peak ~ args + temps
    peak = arg_b + tmp_b
    n_params = sum(int(np.prod(s)) for s in stacked_shapes.values())
    n_params += sum(int(np.prod(s)) for s in rest_shapes.values())
    return {
        "model": f"llama-{n_params/1e9:.1f}B",
        "mesh": {ax: int(n) for ax, n in mesh.shape.items()},
        "virtual_stages": V, "accumulate_steps": M,
        "micro_batch": batch_per_micro, "seq_len": seq_len,
        "stash": stash,
        "zero": zero, "zero_stage": zero_stage if zero else 0,
        "per_device": {"argument_bytes": arg_b, "temp_bytes": tmp_b,
                       "output_bytes": out_b, "peak_bytes": peak},
        "hbm_budget_bytes": int(hbm_budget),
        "fits": peak <= hbm_budget,
        "peak_gib": round(peak / (1 << 30), 2),
    }


def save_hybrid_checkpoint(path: str, blocks, edge):
    """Persist hybrid-PP params in the CANONICAL layer-stacked layout, so a
    checkpoint written at one (S, V) pipeline config reloads at any other
    (the reference needs pp_parallel_adaptor.py to convert per-stage
    checkpoints between pp degrees; storing the canonical form makes the
    conversion a reshape at load)."""
    from ..distributed.checkpoint import save_state_dict
    from ..distributed.fleet.meta_parallel.pp_sharded import (
        stacked_from_blocks)

    sd = {f"stacked.{k}": v for k, v in stacked_from_blocks(blocks).items()}
    sd.update({f"rest.{k}": v for k, v in edge.items()})
    save_state_dict(sd, path)


def load_hybrid_checkpoint(path: str, cfg: LlamaConfig, mesh: Mesh,
                           num_virtual_stages: int = 1):
    """Load a canonical checkpoint into the (possibly different) pipeline
    layout of ``mesh``: returns (blocks, edge) raw-array dicts placed per
    the hybrid specs (same types ``prepare`` produces). Resharding across
    pp degrees is the blocks_from_stacked reshape + device_put.

    NOTE: the restore materializes full arrays on the host before
    device placement (orbax streaming into the BLOCK layout would need
    per-leaf target structs — the canonical layout is reshaped, which
    tensorstore cannot express). Fine single-host; multi-host 65B restores
    should build target ShapeDtypeStructs from the model and use
    distributed.checkpoint.load_state_dict directly."""
    from ..core.tensor import Tensor
    from ..distributed.checkpoint import load_state_dict
    from ..distributed.fleet.meta_parallel.pp_sharded import (
        blocks_from_stacked)

    S = int(mesh.shape.get("pp", 1))
    V = int(num_virtual_stages)
    if cfg.num_hidden_layers % (S * V):
        raise ValueError(
            f"{cfg.num_hidden_layers} layers cannot split into {S} stages "
            f"x {V} virtual chunks")
    sd = load_state_dict(path)
    raw = {k: (v._value if isinstance(v, Tensor) else v)
           for k, v in sd.items()}   # type-symmetric with prepare()
    stacked = {k[len("stacked."):]: v for k, v in raw.items()
               if k.startswith("stacked.")}
    rest = {k[len("rest."):]: v for k, v in raw.items()
            if k.startswith("rest.")}
    blocks = blocks_from_stacked(stacked, S, V)
    return (_shard(blocks, block_specs(blocks.keys()), mesh),
            _shard(rest, edge_specs(rest.keys()), mesh))
