"""GPT-3-family causal LM (BASELINE.json config 2: GPT-3 1.3B tensor-parallel).

Pre-LN transformer: learned position embeddings, LayerNorm, GELU MLP —
built from the same TP layer stack as the Llama family (fleet/layers/mpu).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..distributed._spmd import P, constraint
from ..distributed.fleet.layers.mpu import (ColumnParallelLinear,
                                            ParallelCrossEntropy,
                                            RowParallelLinear,
                                            VocabParallelEmbedding)
from ..nn import functional as F
from ..nn.layer.layers import Layer
from ..nn.layer.norm import LayerNorm

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "gpt_config"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 2048
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    intermediate_size: Optional[int] = None  # None → 4*hidden
    max_position_embeddings: int = 2048
    layer_norm_eps: float = 1e-5
    dropout: float = 0.0
    dtype: str = "float32"
    recompute: str = "none"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size


_PRESETS = {
    "tiny": (64, 2, 4, 256, 128),
    "125m": (768, 12, 12, 50304, 2048),
    "1b3":  (2048, 24, 16, 50304, 2048),
    "6b7":  (4096, 32, 32, 50304, 2048),
}


def gpt_config(preset: str = "tiny", **overrides) -> GPTConfig:
    h, l, a, v, m = _PRESETS[preset]
    cfg = GPTConfig(hidden_size=h, num_hidden_layers=l, num_attention_heads=a,
                    vocab_size=v, max_position_embeddings=m)
    for k, val in overrides.items():
        setattr(cfg, k, val)
    return cfg


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        h = config.hidden_size
        self.qkv_proj = ColumnParallelLinear(h, 3 * h, has_bias=True,
                                             gather_output=False)
        self.out_proj = RowParallelLinear(h, h, has_bias=True,
                                          input_is_parallel=True)

    def forward(self, x):
        cfg = self.config
        b, s = x.shape[0], x.shape[1]
        nh, hd = cfg.num_attention_heads, cfg.head_dim
        qkv = self.qkv_proj(x)

        def split_heads(t):
            # [B,S,3H] → 3×[B,S,nh,hd]; qkv packed head-major so the mp shard
            # of the fused dim stays a contiguous block of heads
            t = t.reshape(b, s, 3, nh, hd)
            return t[:, :, 0], t[:, :, 1], t[:, :, 2]

        q, k, v = apply_op(split_heads, qkv, op_name="split_qkv")
        q = constraint(q, P("dp", None, "mp", None))
        k = constraint(k, P("dp", None, "mp", None))
        v = constraint(v, P("dp", None, "mp", None))
        ctx, _ = F.flash_attention(q, k, v, causal=True,
                                   dropout=cfg.dropout,
                                   training=self.training)
        ctx = apply_op(lambda c: c.reshape(b, s, nh * hd), ctx,
                       op_name="merge_heads")
        ctx = constraint(ctx, P("dp", None, "mp"))
        return self.out_proj(ctx)


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__(dtype=config.dtype)
        self.fc_in = ColumnParallelLinear(config.hidden_size, config.ffn_size,
                                          has_bias=True, gather_output=False)
        self.fc_out = RowParallelLinear(config.ffn_size, config.hidden_size,
                                        has_bias=True, input_is_parallel=True)

    def forward(self, x):
        return self.fc_out(F.gelu(self.fc_in(x), approximate=True))


class GPTDecoderLayer(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__(dtype=config.dtype)
        self.ln_1 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.mlp = GPTMLP(config)
        self.dropout = config.dropout

    def forward(self, x):
        h = self.attn(self.ln_1(x))
        if self.dropout:
            h = F.dropout(h, self.dropout, training=self.training)
        x = x + h
        h = self.mlp(self.ln_2(x))
        if self.dropout:
            h = F.dropout(h, self.dropout, training=self.training)
        x = x + h
        return constraint(x, P("dp", None, None))


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__(dtype=config.dtype)
        from ..core.dtype import get_default_dtype, set_default_dtype
        from ..nn.layer.container import LayerList

        self.config = config
        prev = get_default_dtype()
        set_default_dtype(config.dtype)
        try:
            self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                       config.hidden_size)
            self.embed_positions = VocabParallelEmbedding(
                config.max_position_embeddings, config.hidden_size)
            self.layers = LayerList([GPTDecoderLayer(config)
                                     for _ in range(config.num_hidden_layers)])
            self.ln_f = LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_eps)
        finally:
            set_default_dtype(prev)

    def forward(self, input_ids):
        s = input_ids.shape[1]
        pos = apply_op(lambda ids: jnp.arange(s, dtype=jnp.int32)[None, :],
                       input_ids, op_name="positions")
        x = self.embed_tokens(input_ids) + self.embed_positions(pos)
        x = constraint(x, P("dp", None, None))
        for layer in self.layers:
            if self.config.recompute == "full" and self.training:
                from ..distributed.fleet.recompute import recompute

                x = recompute(layer, x)
            else:
                x = layer(x)
        return self.ln_f(x)


class GPTForCausalLM(Layer):
    IGNORE_INDEX = -100

    def __init__(self, config: GPTConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        from ..core.dtype import get_default_dtype, set_default_dtype

        self.model = GPTModel(config)
        prev = get_default_dtype()
        set_default_dtype(config.dtype)
        try:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=False)
        finally:
            set_default_dtype(prev)
        self.loss_fn = ParallelCrossEntropy(ignore_index=self.IGNORE_INDEX)

    def forward(self, input_ids, labels=None):
        hidden = self.model(input_ids)
        logits = self.lm_head(hidden)
        if labels is None:
            return logits
        from ._utils import masked_lm_loss

        return masked_lm_loss(self.loss_fn(logits, labels), labels,
                              self.IGNORE_INDEX)
