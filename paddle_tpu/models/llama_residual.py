"""Hand-split Llama decoder layer: forward returning stashable residuals,
backward consuming them — the 1F1B double-forward eliminator.

The compiled 1F1B schedule (pp_sharded) originally rematerialized each
chunk's forward inside per-tick ``jax.vjp`` (~33% extra FLOPs — the
forward runs once to feed the pipeline and AGAIN inside the backward
tick's vjp). The reference instead stores activations between the forward
and backward micro-steps (meta_parallel/pipeline_parallel.py:372 holds
``_forward_step`` outputs until ``_backward_step`` :677). This module is
the TPU equivalent: the layer backward is written BY HAND as a pure
function of (params, residuals, cotangent), so residuals — plain arrays —
ride the schedule's stash instead of a vjp closure, and no weight copies
enter the carry (params are passed explicitly at the backward tick).

What gets stashed per layer (``LayerResiduals``): the layer input, post-rope
q/k, v, the attention context + its log-sum-exp (the flash-attention
backward contract, ops/flash_residual.py), the post-attention residual
stream, and the two MLP pre-activations. Everything else (RMS norms, RoPE,
SiLU) is elementwise and recomputed inside the backward — their cost is
noise next to the matmuls, which are never re-run. Matmul backwards are
hand-written (dW = x^T g, dx = g W^T); elementwise backwards reuse local
``jax.vjp`` (cheap, and immune to hand-derivation slips).

Grad parity vs ``jax.vjp`` of the fused forward is asserted in
tests/test_pp_resid.py, together with a compiled-HLO FLOPs bound.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .llama import LlamaConfig, _rope_cos_sin, apply_rotary_emb
from .llama_functional import _rms

__all__ = ["LayerResiduals", "layer_fwd_res", "layer_bwd_res",
           "make_body_fwd_bwd"]


class LayerResiduals(NamedTuple):
    """Stashable activations of one decoder layer (see module docstring)."""
    x: jax.Array        # layer input                     [B, S, H]
    qh: jax.Array       # post-rope queries               [B, S, nh, hd]
    kh: jax.Array       # post-rope keys                  [B, S, kvh, hd]
    vh: jax.Array       # values                          [B, S, kvh, hd]
    ctx: jax.Array      # attention context               [B, S, nh, hd]
    lse: jax.Array      # attention log-sum-exp fp32      [B, nh, S]
    x2: jax.Array       # post-attention residual stream  [B, S, H]
    zg: jax.Array       # gate pre-activation             [B, S, I]
    u: jax.Array        # up projection                   [B, S, I]


def layer_fwd_res(lp: Dict[str, Any], x, cos, sin, cfg: LlamaConfig
                  ) -> Tuple[jax.Array, LayerResiduals]:
    """Same math as llama_functional._layer_fwd, but attention goes through
    the explicit-residual flash pair and every backward-needed intermediate
    is returned."""
    from ..ops.flash_residual import flash_fwd_res

    b, s, h = x.shape
    hd = cfg.head_dim
    xn = _rms(x, lp["input_layernorm.weight"], cfg.rms_norm_eps)
    q = xn @ lp["self_attn.q_proj.weight"]
    k = xn @ lp["self_attn.k_proj.weight"]
    v = xn @ lp["self_attn.v_proj.weight"]
    qh = apply_rotary_emb(q.reshape(b, s, cfg.num_attention_heads, hd),
                          cos, sin)
    kh = apply_rotary_emb(k.reshape(b, s, cfg.kv_heads, hd), cos, sin)
    vh = v.reshape(b, s, cfg.kv_heads, hd)
    ctx, lse = flash_fwd_res(qh, kh, vh, causal=True)
    x2 = x + ctx.reshape(b, s, -1) @ lp["self_attn.o_proj.weight"]
    xn2 = _rms(x2, lp["post_attention_layernorm.weight"], cfg.rms_norm_eps)
    zg = xn2 @ lp["mlp.gate_proj.weight"]
    u = xn2 @ lp["mlp.up_proj.weight"]
    y = x2 + (jax.nn.silu(zg) * u) @ lp["mlp.down_proj.weight"]
    return y, LayerResiduals(x, qh, kh, vh, ctx, lse, x2, zg, u)


def layer_bwd_res(lp: Dict[str, Any], res: LayerResiduals, gy, cos, sin,
                  cfg: LlamaConfig) -> Tuple[Dict[str, Any], jax.Array]:
    """(grad_layer_params, grad_layer_input) from stashed residuals.
    Linear in ``gy``. Matmuls run exactly once (their transposes); only
    elementwise ops (rms/rope/silu) are recomputed."""
    from ..ops.flash_residual import flash_bwd_res

    x, qh, kh, vh, ctx, lse, x2, zg, u = res
    b, s, h = x.shape
    hd = cfg.head_dim
    eps = cfg.rms_norm_eps
    w_ln2 = lp["post_attention_layernorm.weight"]
    w_ln1 = lp["input_layernorm.weight"]

    # ---- MLP ----
    gate = jax.nn.silu(zg)
    gu = gate * u
    d_gu = gy @ lp["mlp.down_proj.weight"].T
    dWd = jnp.einsum("bsi,bsh->ih", gu, gy)
    du = d_gu * gate
    sg = jax.nn.sigmoid(zg)
    dzg = d_gu * u * (sg * (1.0 + zg * (1.0 - sg)))      # silu'
    xn2, rms2_vjp = jax.vjp(lambda xx, ww: _rms(xx, ww, eps), x2, w_ln2)
    dWg = jnp.einsum("bsh,bsi->hi", xn2, dzg)
    dWu = jnp.einsum("bsh,bsi->hi", xn2, du)
    dxn2 = dzg @ lp["mlp.gate_proj.weight"].T + du @ lp["mlp.up_proj.weight"].T
    dx2_rms, dw_ln2 = rms2_vjp(dxn2)
    dx2 = gy + dx2_rms

    # ---- attention output projection ----
    ctxf = ctx.reshape(b, s, -1)
    dctxf = dx2 @ lp["self_attn.o_proj.weight"].T
    dWo = jnp.einsum("bsc,bsh->ch", ctxf, dx2)
    dctx = dctxf.reshape(b, s, cfg.num_attention_heads, hd)

    # ---- flash attention ----
    dqh, dkh, dvh = flash_bwd_res(qh, kh, vh, ctx, lse, dctx, causal=True)

    # ---- RoPE transpose: rotation by -theta (rope is orthogonal) ----
    dq = apply_rotary_emb(dqh, cos, -sin).reshape(b, s, -1)
    dk = apply_rotary_emb(dkh, cos, -sin).reshape(b, s, -1)
    dv = dvh.reshape(b, s, -1)

    # ---- qkv projections + input norm ----
    xn1, rms1_vjp = jax.vjp(lambda xx, ww: _rms(xx, ww, eps), x, w_ln1)
    dWq = jnp.einsum("bsh,bsc->hc", xn1, dq)
    dWk = jnp.einsum("bsh,bsc->hc", xn1, dk)
    dWv = jnp.einsum("bsh,bsc->hc", xn1, dv)
    dxn1 = (dq @ lp["self_attn.q_proj.weight"].T
            + dk @ lp["self_attn.k_proj.weight"].T
            + dv @ lp["self_attn.v_proj.weight"].T)
    dx_rms, dw_ln1 = rms1_vjp(dxn1)
    dx = dx2 + dx_rms

    g_lp = {
        "input_layernorm.weight": dw_ln1.astype(w_ln1.dtype),
        "post_attention_layernorm.weight": dw_ln2.astype(w_ln2.dtype),
        "self_attn.q_proj.weight": dWq.astype(lp["self_attn.q_proj.weight"].dtype),
        "self_attn.k_proj.weight": dWk.astype(lp["self_attn.k_proj.weight"].dtype),
        "self_attn.v_proj.weight": dWv.astype(lp["self_attn.v_proj.weight"].dtype),
        "self_attn.o_proj.weight": dWo.astype(lp["self_attn.o_proj.weight"].dtype),
        "mlp.gate_proj.weight": dWg.astype(lp["mlp.gate_proj.weight"].dtype),
        "mlp.up_proj.weight": dWu.astype(lp["mlp.up_proj.weight"].dtype),
        "mlp.down_proj.weight": dWd.astype(lp["mlp.down_proj.weight"].dtype),
    }
    return g_lp, dx.astype(x.dtype)


def make_body_fwd_bwd(cfg: LlamaConfig):
    """(body_fwd, body_bwd) over a stacked chunk (leaves lead with lpc) for
    pp_sharded.build_sharded_1f1b_resid_grad_fn:

    - ``body_fwd(chunk, h) -> (h_out, res)`` — forward scan collecting
      per-layer residuals (res leaves lead with lpc).
    - ``body_bwd(chunk, res, g) -> (g_chunk, g_h)`` — REVERSE scan through
      the hand-split layer backward; g_chunk comes out stacked in the
      chunk's own layout.
    """

    def body_fwd(chunk, h):
        cos, sin = _rope_cos_sin(h.shape[1], cfg.head_dim, cfg.rope_theta,
                                 h.dtype)

        def step(x, lp):
            y, res = layer_fwd_res(lp, x, cos, sin, cfg)
            return y, res

        h_out, res = jax.lax.scan(step, h, chunk)
        return h_out, res

    def body_bwd(chunk, res, g):
        cos, sin = _rope_cos_sin(g.shape[1], cfg.head_dim, cfg.rope_theta,
                                 g.dtype)

        def step(gy, lp_res):
            lp, r = lp_res
            g_lp, g_x = layer_bwd_res(lp, r, gy, cos, sin, cfg)
            return g_x, g_lp

        g_h, g_chunk = jax.lax.scan(step, g, (chunk, res), reverse=True)
        return g_chunk, g_h

    return body_fwd, body_bwd
