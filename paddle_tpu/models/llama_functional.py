"""Scan-over-layers functional Llama — the TPU compile-time architecture.

A 24-80 layer decoder inlined per-layer produces an HLO linear in depth;
with every layer structurally identical, the TPU-idiomatic form stacks the
per-layer parameters into leading-[L] arrays and runs ONE ``lax.scan`` over
the decoder body, so the layer compiles once regardless of depth (and remat
is a single ``jax.checkpoint`` on the scan body — exactly 1F1B-style
activation memory: one layer's interior live at a time plus L carried
boundaries).

This is the functional counterpart of ``models/llama.py`` (same math, same
parameter names — ``stack_params``/``unstack_params`` convert); the Layer
API stays the eager/TP-annotated source of truth, this module is the
high-performance jit target used by ``bench.py`` and large-scale training.
Reference analog: the reference reaches the same goal with a static graph +
while-op over fused_multi_transformer layers.
"""
from __future__ import annotations

import math
import re
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from .llama import LlamaConfig, _rope_cos_sin, apply_rotary_emb

__all__ = ["stack_params", "unstack_params", "build_loss_fn",
           "build_train_step"]

_LAYER_RE = re.compile(r"^model\.layers\.(\d+)\.(.+)$")


def stack_params(params: Dict[str, Any], cfg: LlamaConfig):
    """Split a named-parameter dict into (stacked_layer_pytree, rest):
    ``model.layers.i.K`` entries become ``stacked[K]`` with leading dim L."""
    per_layer: Dict[str, list] = {}
    rest: Dict[str, Any] = {}
    for k, v in params.items():
        m = _LAYER_RE.match(k)
        if m:
            per_layer.setdefault(m.group(2), []).append((int(m.group(1)), v))
        else:
            rest[k] = v
    stacked = {}
    for k, items in per_layer.items():
        items.sort(key=lambda t: t[0])
        assert len(items) == cfg.num_hidden_layers, (k, len(items))
        stacked[k] = jnp.stack([v for _, v in items])
    return stacked, rest


def unstack_params(stacked: Dict[str, Any], rest: Dict[str, Any]):
    """Inverse of stack_params (for checkpoint interop with the Layer API)."""
    out = dict(rest)
    for k, v in stacked.items():
        for i in range(v.shape[0]):
            out[f"model.layers.{i}.{k}"] = v[i]
    return out


def _rms(x, w, eps):
    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + eps)
    return (y * w.astype(jnp.float32)).astype(x.dtype)


def _layer_fwd(lp: Dict[str, Any], x, cos, sin, cfg: LlamaConfig):
    """One decoder layer, pure jax. Weight layout matches mp_layers Linear
    (weight [in, out]); attention via the GQA flash kernel on TPU."""
    b, s, h = x.shape
    hd = cfg.head_dim
    xn = _rms(x, lp["input_layernorm.weight"], cfg.rms_norm_eps)
    q = xn @ lp["self_attn.q_proj.weight"]
    k = xn @ lp["self_attn.k_proj.weight"]
    v = xn @ lp["self_attn.v_proj.weight"]
    qh = apply_rotary_emb(q.reshape(b, s, cfg.num_attention_heads, hd),
                          cos, sin)
    kh = apply_rotary_emb(k.reshape(b, s, cfg.kv_heads, hd), cos, sin)
    vh = v.reshape(b, s, cfg.kv_heads, hd)
    from ..ops.pallas import flash_attention

    ctx = flash_attention(qh, kh, vh, causal=True)
    # named for remat="attn_out": saving ONLY the flash output removes
    # the refwd-flash bucket (~22ms/step at 350M, PERF.md decomposition)
    # for B·S·H_model bytes/layer — ~800MB at the bench config, far less
    # than remat="dots"'s rejected 8.4GB of dot outputs
    from jax.ad_checkpoint import checkpoint_name

    ctx = checkpoint_name(ctx, "attn_out")
    ctx = ctx.reshape(b, s, cfg.num_attention_heads * hd)
    x = x + ctx @ lp["self_attn.o_proj.weight"]
    xn = _rms(x, lp["post_attention_layernorm.weight"], cfg.rms_norm_eps)
    gate = jax.nn.silu(xn @ lp["mlp.gate_proj.weight"])
    up = xn @ lp["mlp.up_proj.weight"]
    return x + (gate * up) @ lp["mlp.down_proj.weight"]


def _remat_policy(remat):
    """Map a remat spec to a jax.checkpoint policy. True/"full" = save
    nothing (recompute everything, ~1.33x FLOPs); "dots" = save matmul
    outputs (recompute only elementwise, near-zero FLOP overhead at the
    cost of per-layer dot residuals); False/"none" = no checkpoint."""
    if remat in (True, "full"):
        return {}
    if remat == "attn_out":
        return {"policy":
                jax.checkpoint_policies.save_only_these_names("attn_out")}
    if remat == "dots":
        return {"policy":
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable}
    raise ValueError(f"unknown remat spec {remat!r}")


def forward(stacked, rest, ids, cfg: LlamaConfig, remat=True,
            scan_unroll: int = 1):
    """Logits for [B, S] ids. Decoder runs as scan-over-layers.
    ``scan_unroll`` exposes that many consecutive layers to one XLA
    fusion scope (experiments/exp_dots.py E1 measures whether boundary
    relayouts fuse away; keep 1 until a TPU win is recorded)."""
    x = jnp.take(rest["model.embed_tokens.weight"], ids, axis=0)
    cos, sin = _rope_cos_sin(ids.shape[1], cfg.head_dim, cfg.rope_theta,
                             x.dtype)

    def body(x, lp):
        return _layer_fwd(lp, x, cos, sin, cfg), None

    if remat not in (False, "none"):
        body = jax.checkpoint(body, **_remat_policy(remat))
    x, _ = jax.lax.scan(body, x, stacked, unroll=scan_unroll)
    x = _rms(x, rest["model.norm.weight"], cfg.rms_norm_eps)
    if "lm_head.weight" in rest:
        return x @ rest["lm_head.weight"]
    return x @ rest["model.embed_tokens.weight"].T


def build_loss_fn(cfg: LlamaConfig, remat=True,
                  ignore_index: int = -100, scan_unroll: int = 1):
    """Pure (stacked, rest, ids, labels) -> mean CE loss."""

    def loss_fn(stacked, rest, ids, labels):
        logits = forward(stacked, rest, ids, cfg, remat,
                         scan_unroll=scan_unroll)
        # lse − logit[label] form: never materializes a [B,S,V] fp32
        # log-softmax (the convert fuses into the reduction; the direct
        # form wrote+read an extra ~3x vocab-sized fp32 temp)
        lbl = jnp.clip(labels, 0, cfg.vocab_size - 1)
        lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        tgt = jnp.take_along_axis(logits, lbl[..., None], -1)[..., 0]
        nll = lse - tgt.astype(jnp.float32)
        mask = (labels != ignore_index).astype(jnp.float32)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    return loss_fn


def build_train_step(cfg: LlamaConfig, lr: float = 1e-4,
                     clip_norm: float = 1.0, remat=True,
                     moment_dtype=None, scan_unroll: int = 1):
    """Jittable AdamW train step over (stacked, rest) param pytrees.
    Optimizer state is stacked too — the update compiles once per tensor
    kind, not once per layer. ``moment_dtype=jnp.bfloat16`` halves
    optimizer HBM (the 1.3B-on-one-chip policy; math stays fp32).
    ``remat``/``scan_unroll`` pass through to the loss (exp_dots E1/E5
    levers)."""
    from ..optimizer.functional import (adamw_init, adamw_update,
                                        clip_by_global_norm)

    loss_fn = build_loss_fn(cfg, remat, scan_unroll=scan_unroll)

    def init(stacked, rest):
        return adamw_init({"stacked": stacked, "rest": rest},
                          moment_dtype=moment_dtype)

    def step(stacked, rest, opt_state, ids, labels):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p["stacked"], p["rest"], ids, labels))(
                {"stacked": stacked, "rest": rest})
        grads, _ = clip_by_global_norm(grads, clip_norm)
        opt_state, params = adamw_update(
            grads, opt_state, {"stacked": stacked, "rest": rest}, lr=lr)
        return params["stacked"], params["rest"], opt_state, loss

    return step, init
