"""Flagship model families built on the TP layer stack.

Reference analog: PaddleNLP-style model zoo driven by the framework's fleet
TP/PP layers (the reference repo itself ships the layer stack —
fleet/layers/mpu — and fused transformer ops; the model graph lives here so
benchmarks and the driver entry have a first-class citizen to run).
"""
from . import llama
from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel,
                    llama_config)

__all__ = ["llama", "LlamaConfig", "LlamaForCausalLM", "LlamaModel",
           "llama_config"]


# lazy model families: submodule name → its public names
_LAZY = {
    "gpt": ("GPTConfig", "GPTModel", "GPTForCausalLM", "gpt_config"),
    "ernie": ("ErnieMoEConfig", "ErnieMoEModel", "ErnieMoEForMaskedLM",
              "ernie_moe_config"),
}


def __getattr__(name):
    for sub, names in _LAZY.items():
        if name == sub or name in names:
            import importlib

            mod = importlib.import_module(f".{sub}", __name__)
            globals()[sub] = mod
            for n in names:
                globals()[n] = getattr(mod, n)
            return globals()[name]
    raise AttributeError(name)


def __dir__():
    out = set(globals()) | set(_LAZY)
    for names in _LAZY.values():
        out |= set(names)
    return sorted(out)
