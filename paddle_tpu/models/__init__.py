"""Flagship model families built on the TP layer stack.

Reference analog: PaddleNLP-style model zoo driven by the framework's fleet
TP/PP layers (the reference repo itself ships the layer stack —
fleet/layers/mpu — and fused transformer ops; the model graph lives here so
benchmarks and the driver entry have a first-class citizen to run).
"""
from . import llama
from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel,
                    llama_config)

__all__ = ["llama", "LlamaConfig", "LlamaForCausalLM", "LlamaModel",
           "llama_config"]


_GPT_NAMES = ("GPTConfig", "GPTModel", "GPTForCausalLM", "gpt_config")


def __getattr__(name):
    if name == "gpt" or name in _GPT_NAMES:
        import importlib

        mod = importlib.import_module(".gpt", __name__)
        globals()["gpt"] = mod
        for n in _GPT_NAMES:
            globals()[n] = getattr(mod, n)
        return globals()[name]
    raise AttributeError(name)


def __dir__():
    return sorted(set(globals()) | {"gpt"} | set(_GPT_NAMES))
