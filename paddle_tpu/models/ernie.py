"""ERNIE-MoE encoder (BASELINE.json config 4: ERNIE-MoE expert-parallel).

Bidirectional pre-LN transformer encoder in the ERNIE 3.0 shape with
Mixture-of-Experts FFN on alternating layers (the ERNIE 3.0 Titan /
reference incubate MoE training recipe: dense attention everywhere, GShard
top-2 dispatched expert MLPs over the ``ep`` mesh axis —
incubate/distributed/models/moe/moe_layer.py:263) and an MLM head for
pretraining. Non-MoE pieces reuse the TP layer stack (fleet/layers/mpu),
so the model composes dp x mp x ep out of the box.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp

from ..core.autograd import apply_op
from ..distributed._spmd import P, constraint
from ..distributed.fleet.layers.mpu import (ColumnParallelLinear,
                                            RowParallelLinear,
                                            VocabParallelEmbedding)
from ..nn import functional as F
from ..nn.layer.layers import Layer
from ..nn.layer.norm import LayerNorm

__all__ = ["ErnieMoEConfig", "ErnieMoEModel", "ErnieMoEForMaskedLM",
           "ernie_moe_config"]


@dataclass
class ErnieMoEConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: Optional[int] = None      # None → 4*hidden
    num_experts: int = 8
    top_k: int = 2
    moe_every: int = 2          # MoE FFN on every Nth layer (1-indexed)
    capacity_factor: float = 1.2
    max_position_embeddings: int = 512
    type_vocab_size: int = 4
    layer_norm_eps: float = 1e-5
    dropout: float = 0.0
    dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size


_PRESETS = {
    # name: (hidden, layers, heads, experts, vocab)
    "tiny": (64, 2, 4, 4, 256),
    "base": (768, 12, 12, 8, 40000),
    "large": (1024, 24, 16, 64, 40000),
}


def ernie_moe_config(preset: str = "tiny", **overrides) -> ErnieMoEConfig:
    h, l, a, e, v = _PRESETS[preset]
    cfg = ErnieMoEConfig(hidden_size=h, num_hidden_layers=l,
                         num_attention_heads=a, num_experts=e, vocab_size=v)
    for k, val in overrides.items():
        setattr(cfg, k, val)
    return cfg


class _SelfAttention(Layer):
    def __init__(self, config: ErnieMoEConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        h = config.hidden_size
        self.qkv_proj = ColumnParallelLinear(h, 3 * h, has_bias=True,
                                             gather_output=False)
        self.out_proj = RowParallelLinear(h, h, has_bias=True,
                                          input_is_parallel=True)

    def forward(self, x):
        cfg = self.config
        b, s = x.shape[0], x.shape[1]
        nh, hd = cfg.num_attention_heads, cfg.head_dim
        qkv = self.qkv_proj(x)

        def split_heads(t):
            t = t.reshape(b, s, 3, nh, hd)
            return t[:, :, 0], t[:, :, 1], t[:, :, 2]

        q, k, v = apply_op(split_heads, qkv, op_name="split_qkv")
        ctx, _ = F.flash_attention(q, k, v, causal=False,
                                   dropout=cfg.dropout,
                                   training=self.training)
        ctx = apply_op(lambda c: c.reshape(b, s, nh * hd), ctx,
                       op_name="merge_heads")
        return self.out_proj(ctx)


def _make_moe_ffn(config: ErnieMoEConfig):
    from .. import nn
    from ..incubate.distributed.models.moe import MoELayer

    experts = [
        nn.Sequential(nn.Linear(config.hidden_size, config.ffn_size),
                      nn.GELU(),
                      nn.Linear(config.ffn_size, config.hidden_size))
        for _ in range(config.num_experts)
    ]
    return MoELayer(d_model=config.hidden_size, experts=experts,
                    gate={"type": "gshard", "top_k": config.top_k},
                    capacity_factor=config.capacity_factor)


class _DenseFFN(Layer):
    def __init__(self, config: ErnieMoEConfig):
        super().__init__(dtype=config.dtype)
        self.fc_in = ColumnParallelLinear(config.hidden_size,
                                          config.ffn_size, has_bias=True,
                                          gather_output=False)
        self.fc_out = RowParallelLinear(config.ffn_size, config.hidden_size,
                                        has_bias=True,
                                        input_is_parallel=True)

    def forward(self, x):
        return self.fc_out(F.gelu(self.fc_in(x), approximate=True))


class ErnieMoEEncoderLayer(Layer):
    def __init__(self, config: ErnieMoEConfig, use_moe: bool):
        super().__init__(dtype=config.dtype)
        self.ln_1 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_eps)
        self.attn = _SelfAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_eps)
        self.ffn = _make_moe_ffn(config) if use_moe else _DenseFFN(config)
        self.use_moe = use_moe

    def forward(self, x):
        x = x + self.attn(self.ln_1(x))
        x = x + self.ffn(self.ln_2(x))
        return constraint(x, P("dp", None, None))


class ErnieMoEModel(Layer):
    def __init__(self, config: ErnieMoEConfig):
        super().__init__(dtype=config.dtype)
        from ..core.dtype import get_default_dtype, set_default_dtype
        from ..nn.layer.common import Embedding
        from ..nn.layer.container import LayerList

        self.config = config
        # sublayers create params via the default dtype (same pattern as
        # GPTModel): config.dtype must actually apply
        prev = get_default_dtype()
        set_default_dtype(config.dtype)
        try:
            self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                       config.hidden_size)
            self.embed_pos = Embedding(config.max_position_embeddings,
                                       config.hidden_size)
            self.embed_type = Embedding(config.type_vocab_size,
                                        config.hidden_size)
            self.layers = LayerList([
                ErnieMoEEncoderLayer(
                    config, use_moe=((i + 1) % config.moe_every == 0))
                for i in range(config.num_hidden_layers)
            ])
            self.norm = LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_eps)
        finally:
            set_default_dtype(prev)

    def forward(self, input_ids, token_type_ids=None):
        import paddle_tpu as paddle

        b, s = input_ids.shape[0], input_ids.shape[1]
        pos = paddle.arange(s).unsqueeze(0)
        x = self.embed_tokens(input_ids) + self.embed_pos(pos)
        if token_type_ids is not None:
            x = x + self.embed_type(token_type_ids)
        for layer in self.layers:
            x = layer(x)
        return self.norm(x)

    def moe_aux_loss(self):
        """Sum of the GShard load-balancing losses of every MoE layer
        (gates stash them via BaseGate.set_loss during forward)."""
        total = None
        for layer in self.layers:
            if layer.use_moe:
                l = layer.ffn.gate.get_loss(clear=True)
                if l is not None:
                    total = l if total is None else total + l
        return total


class ErnieMoEForMaskedLM(Layer):
    """MLM pretraining head (ERNIE's knowledge-masking objective reduces
    to masked-token CE at the modeling level)."""

    def __init__(self, config: ErnieMoEConfig):
        super().__init__(dtype=config.dtype)
        from ..core.dtype import get_default_dtype, set_default_dtype

        self.ernie = ErnieMoEModel(config)
        prev = get_default_dtype()
        set_default_dtype(config.dtype)
        try:
            self.lm_head = ColumnParallelLinear(config.hidden_size,
                                                config.vocab_size,
                                                has_bias=False,
                                                gather_output=True)
        finally:
            set_default_dtype(prev)

    def forward(self, input_ids, labels=None, token_type_ids=None,
                aux_loss_weight: float = 0.01):
        h = self.ernie(input_ids, token_type_ids)
        logits = self.lm_head(h)
        if labels is None:
            return logits
        loss = F.cross_entropy(
            logits.reshape([-1, self.ernie.config.vocab_size]),
            labels.reshape([-1]), ignore_index=-100)
        aux = self.ernie.moe_aux_loss()
        if aux is not None:
            loss = loss + aux_loss_weight * aux
        return loss, logits
