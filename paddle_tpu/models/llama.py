"""Llama-family causal LM, TPU-native.

Architecture: RMSNorm / RoPE / GQA attention / SwiGLU — the Llama-2 recipe,
built from the tensor-parallel layer stack
(distributed/fleet/layers/mpu/mp_layers.py) so the SAME module runs
single-chip, TP-sharded under GSPMD (weights carry PartitionSpecs), or
inside shard_map. Reference analogs: the reference's fused transformer
blocks (fluid/operators/fused/fused_multi_transformer_op.cu) define the
fusion targets; attention runs through nn.functional.flash_attention which
routes to the Pallas kernel on TPU.

Sharding plan (scaling-book "2D finalized" layout):
- embed/lm_head:  vocab on mp                       P('mp', None)
- q/k/v/gate/up:  out-dim on mp (column parallel)   P(None, 'mp')
- o/down:         in-dim on mp (row parallel)       P('mp', None)
- activations:    batch on dp(+sharding), heads/ffn on mp via constraints
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from ..distributed._spmd import P, constraint, set_pspec
from ..distributed.fleet.layers.mpu import (ColumnParallelLinear,
                                            ParallelCrossEntropy,
                                            RowParallelLinear,
                                            VocabParallelEmbedding)
from ..nn import functional as F
from ..nn.layer.layers import Layer
from ..nn.layer.norm import RMSNorm

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM", "llama_config"]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: Optional[int] = None  # GQA; None → MHA
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    dtype: str = "float32"
    # remat policy for the decoder stack ("none" | "full")
    recompute: str = "none"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def kv_heads(self) -> int:
        return self.num_key_value_heads or self.num_attention_heads


_PRESETS = {
    # name: (hidden, inter, layers, heads, kv_heads, vocab)
    "tiny":  (64, 176, 2, 4, 4, 256),        # CI / dryrun
    "350m":  (1024, 2816, 24, 16, 16, 32000),
    "1b3":   (2048, 5504, 24, 16, 16, 32000),
    "7b":    (4096, 11008, 32, 32, 32, 32000),
    "13b":   (5120, 13824, 40, 40, 40, 32000),
    "65b":   (8192, 22016, 80, 64, 64, 32000),  # Llama-2-65B: MHA (kv=64)
}


def llama_config(preset: str = "tiny", **overrides) -> LlamaConfig:
    h, i, l, a, kv, v = _PRESETS[preset]
    cfg = LlamaConfig(hidden_size=h, intermediate_size=i, num_hidden_layers=l,
                      num_attention_heads=a, num_key_value_heads=kv,
                      vocab_size=v)
    for k, val in overrides.items():
        setattr(cfg, k, val)
    return cfg


def _rope_cos_sin(seq_len: int, head_dim: int, theta: float, dtype):
    """Precompute RoPE cos/sin tables [seq, head_dim//2]."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def _lora_add(x, y, lora, name):
    """Add the per-row LoRA delta for target projection ``name``:
    ``y + (x @ A[idx]^T) @ B[idx]^T`` with each ROW's factors gathered
    by its ``adapter_idx`` — the S-LoRA batched-adapter shape, per-slot
    weights as a device-vector gather inside the one compiled program
    (the PR 2 invariant extended from sampling params to weights).

    ``lora`` is ``(bank, idx)``: ``bank`` maps target names to THIS
    layer's stacked factors ``A [K+1, r, d_in]`` / ``B [K+1, d_out, r]``
    (index 0 = base model, rows pinned to zeros — the gathered delta is
    exactly 0.0, so base rows stay bitwise what a LoRA-free forward
    produces); ``idx`` is the per-row ``[B]`` int32 adapter index.
    Works for any sequence width (prefill S, decode 1, spec-verify W).
    The LoRA scaling (alpha/r) is folded into B at install time."""
    if lora is None:
        return y
    bank, idx = lora
    ab = bank.get(name)
    if ab is None:
        return y
    A, B = ab

    def add(xv, yv, Av, Bv, iv):
        a_sel = jnp.take(Av, iv, axis=0)      # [B, r, d_in]
        b_sel = jnp.take(Bv, iv, axis=0)      # [B, d_out, r]
        t = jnp.einsum("bsd,brd->bsr", xv, a_sel)
        return yv + jnp.einsum("bsr,bor->bso", t,
                               b_sel).astype(yv.dtype)

    return apply_op(add, x, y, A, B, idx, op_name=f"lora_{name}")


def _lora_layer(lora, i):
    """Layer ``i``'s slice of the engine-level LoRA inputs: the bank
    holds per-layer factor stacks ``[L, K+1, r, d]``; each decoder
    layer gathers from its own ``[K+1, r, d]`` slice (``i`` is a trace
    constant, so the slice costs nothing)."""
    if lora is None:
        return None
    bank, idx = lora
    return {t: (A[i], B[i]) for t, (A, B) in bank.items()}, idx


def apply_rotary_emb(x, cos, sin):
    """x: [B, S, H, D]; rotate-half RoPE (reference analog:
    fused_rope_kernel.cu:87 fused_rotary_position_embedding).

    ``cos``/``sin`` are either the shared position tables ``[S, d2]`` or
    already broadcast to x's rank (the ragged-decode path passes per-ROW
    angles ``[B, 1, 1, d2]``).

    On TPU the shared-table form routes to the Pallas fused_rope kernel:
    the half-split of the 128-lane head_dim is VMEM-local there, where the
    jnp slice+concat forms cost two HBM relayouts (measured ~20x slower at
    llama shapes). The per-row form stays in jnp (one token per row)."""
    shared = cos.ndim == 2
    if shared and jax.default_backend() == "tpu" and x.shape[-1] % 2 == 0:
        from ..ops.pallas_kernels import fused_rope

        return fused_rope(x, cos, sin)
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    c = cos[None, :, None, :] if shared else cos
    s = sin[None, :, None, :] if shared else sin
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        h = config.hidden_size
        hd = config.head_dim
        self.num_heads = config.num_attention_heads
        self.kv_heads = config.kv_heads
        self.q_proj = ColumnParallelLinear(h, self.num_heads * hd,
                                           has_bias=False, gather_output=False)
        self.k_proj = ColumnParallelLinear(h, self.kv_heads * hd,
                                           has_bias=False, gather_output=False)
        self.v_proj = ColumnParallelLinear(h, self.kv_heads * hd,
                                           has_bias=False, gather_output=False)
        self.o_proj = RowParallelLinear(self.num_heads * hd, h,
                                        has_bias=False, input_is_parallel=True)

    def _qkv_lora(self, x, lora):
        """Shared q/k/v projection + per-row LoRA delta (every cached/
        decode path's head; ``lora=None`` is exactly the pre-LoRA
        projection)."""
        q = _lora_add(x, self.q_proj(x), lora, "q")
        k = _lora_add(x, self.k_proj(x), lora, "k")
        v = _lora_add(x, self.v_proj(x), lora, "v")
        return q, k, v

    def _o_lora(self, ctx, lora):
        return _lora_add(ctx, self.o_proj(ctx), lora, "o")

    def forward_with_cache(self, x, cos_full, sin_full, cache, pos,
                           lora=None, tp=None):
        """Serving path: attend over a preallocated KV cache.

        x: [B, S, h] (S>1 = prefill, S==1 = decode); cache: (k, v) jnp
        arrays [B, S_max, Hkv, hd]; pos: int32 scalar — tokens already in
        the cache. Returns (out, new_cache). The decode step is the
        masked_multihead_attention analog (reference
        fused_multi_transformer_op.cu.h:745); prefill uses the flash path.
        ``lora`` (here and on every decode variant below) is the
        per-row batched-adapter input — see :func:`_lora_add`;
        ``tp`` is the serving engine's tensor-parallel handle
        ``(mesh, axis)`` (see ``inference/tp.py``) — threaded into the
        attention ops' shard_map wrap so each mesh shard runs the
        kernel on its local head slice (None = single-device trace,
        byte-identical to pre-TP).
        """
        b, s = x.shape[0], x.shape[1]
        hd = self.config.head_dim
        q, k, v = self._qkv_lora(x, lora)
        k_cache, v_cache = cache

        def attend(qv, kv, vv, kc, vc):
            # rope at absolute positions [pos, pos+s)
            cs = jax.lax.dynamic_slice_in_dim(cos_full, pos, s, axis=0)
            sn = jax.lax.dynamic_slice_in_dim(sin_full, pos, s, axis=0)
            qh = apply_rotary_emb(qv.reshape(b, s, self.num_heads, hd), cs, sn)
            kh = apply_rotary_emb(kv.reshape(b, s, self.kv_heads, hd), cs, sn)
            vh = vv.reshape(b, s, self.kv_heads, hd)
            kc = jax.lax.dynamic_update_slice_in_dim(
                kc, kh.astype(kc.dtype), pos, axis=1)
            vc = jax.lax.dynamic_update_slice_in_dim(
                vc, vh.astype(vc.dtype), pos, axis=1)
            lens = jnp.full((b,), pos + s, jnp.int32)
            if s == 1:
                from ..ops._decode import gqa_decode_attention

                ctx = gqa_decode_attention(
                    qh[:, 0], kc, vc, lens,
                    tp=tp)[:, None]                       # [B, 1, Hq, hd]
            elif isinstance(pos, int) and pos == 0:
                # fresh prefill (the generation engine's case): plain causal
                # flash over just the prompt — attending the full
                # preallocated cache width would cost max_len/s extra work
                from ..ops.pallas import flash_attention as _flash

                ctx = _flash(qh, kh, vh, causal=True, tp=tp)
            else:
                # chunked prefill / spec-verify at a traced offset: the
                # online-softmax prefix attention shares its reduction
                # structure with the one-shot flash fallback, so chunked
                # and padded-bucket prefill reproduce single-shot prefill
                # bitwise (ops/pallas.prefix_chunk_attention)
                from ..ops.pallas import prefix_chunk_attention

                ctx = prefix_chunk_attention(qh, kc, vc, pos, tp=tp)
            return ctx.reshape(b, s, self.num_heads * hd), kc, vc

        ctx, kc, vc = apply_op(attend, q, k, v, k_cache, v_cache,
                               op_name="cached_attention")
        val = lambda t: t.value if isinstance(t, Tensor) else t  # noqa: E731
        return self._o_lora(ctx, lora), (val(kc), val(vc))

    def forward_decode_ragged(self, x, cos_full, sin_full, cache, lens,
                              live, lora=None, tp=None):
        """Ragged decode step: mixed-length rows, padding-free semantics.

        x: [B, 1, h]; lens: [B] int32 tokens already in each ROW's cache
        (per-row positions — rows need not agree); live: [B] bool — only
        live rows write their k/v and advance. Reference: the reference
        decode kernel serves mixed-length batches after remove_padding
        (fused_multi_transformer_op.cu.h:1641) with per-sequence lengths
        (:1680); here the per-row state IS the seq_lens vector the
        decode_mha kernel already takes (its S-block grid skips blocks
        past each row's length, so compute is O(lens[b]), not O(max_len)).
        """
        b = x.shape[0]
        hd = self.config.head_dim
        q, k, v = self._qkv_lora(x, lora)
        kc0, vc0 = cache

        def attend(qv, kv, vv, kc, vc):
            max_len = kc.shape[1]
            idx = jnp.minimum(lens, max_len - 1)
            c = cos_full[idx][:, None, None, :]    # [B, 1, 1, d2] per row
            s = sin_full[idx][:, None, None, :]
            qh = apply_rotary_emb(
                qv.reshape(b, 1, self.num_heads, hd), c, s)[:, 0]
            kh = apply_rotary_emb(
                kv.reshape(b, 1, self.kv_heads, hd), c, s)[:, 0]
            vh = vv.reshape(b, self.kv_heads, hd)
            ar = jnp.arange(b)
            # dead rows re-write their existing cell (no-op write): the
            # scatter stays unconditional = one compiled program
            kw = jnp.where(live[:, None, None], kh.astype(kc.dtype),
                           kc[ar, idx])
            vw = jnp.where(live[:, None, None], vh.astype(vc.dtype),
                           vc[ar, idx])
            kc = kc.at[ar, idx].set(kw)
            vc = vc.at[ar, idx].set(vw)
            from ..ops._decode import gqa_decode_attention

            ctx = gqa_decode_attention(
                qh, kc, vc, lens + live.astype(jnp.int32), tp=tp)
            return ctx.reshape(b, 1, self.num_heads * hd), kc, vc

        ctx, kc, vc = apply_op(attend, q, k, v, kc0, vc0,
                               op_name="ragged_attention")
        val = lambda t: t.value if isinstance(t, Tensor) else t  # noqa: E731
        return self._o_lora(ctx, lora), (val(kc), val(vc))

    def forward_decode_spec(self, x, cos_full, sin_full, cache, lens,
                            live, lora=None, tp=None):
        """Speculative VERIFY step over the dense ragged cache: W query
        positions per row at per-row offsets (x: [B, W, h]; position i
        of row b sits at absolute position ``lens[b] + i``).

        The serving form of the offline spec-verify forward: all W
        tokens' K/V are written at their per-row positions first
        (writes of dead rows or positions past max_len are DROPPED via
        an out-of-range sentinel, so the step stays one compiled
        program), then each query position runs the SAME
        ``gqa_decode_attention`` call the one-token ragged step uses,
        with its own length ``lens + i + 1`` — so position i attends
        exactly the history a sequential decode would have, and when
        the input tokens match the greedy continuation the logits are
        BITWISE what ``forward_decode_ragged`` would have produced one
        token at a time. Rejected drafts leave stale KV past the
        accepted length; every read is length-masked and later writes
        overwrite it (the offline path's documented convention).
        """
        b, w = x.shape[0], x.shape[1]
        hd = self.config.head_dim
        q, k, v = self._qkv_lora(x, lora)
        kc0, vc0 = cache

        def attend(qv, kv, vv, kc, vc):
            max_len = kc.shape[1]
            pos = lens[:, None] + jnp.arange(w, dtype=jnp.int32)[None]
            idx = jnp.minimum(pos, max_len - 1)
            c = cos_full[idx][:, :, None, :]   # [B, W, 1, d2] per row
            s = sin_full[idx][:, :, None, :]
            qh = apply_rotary_emb(qv.reshape(b, w, self.num_heads, hd),
                                  c, s)
            kh = apply_rotary_emb(kv.reshape(b, w, self.kv_heads, hd),
                                  c, s)
            vh = vv.reshape(b, w, self.kv_heads, hd)
            ar = jnp.arange(b)
            # dead rows / positions past the cache -> sentinel row
            # index, dropped (NOT clamped: a clamp would overwrite the
            # last valid cell with draft garbage)
            tgt = jnp.where(live[:, None] & (pos < max_len), pos,
                            max_len)
            kc = kc.at[ar[:, None], tgt].set(kh.astype(kc.dtype),
                                             mode="drop")
            vc = vc.at[ar[:, None], tgt].set(vh.astype(vc.dtype),
                                             mode="drop")
            from ..ops._decode import gqa_decode_attention

            lv = live.astype(jnp.int32)
            # one masked decode attention per window position (W is
            # small and static — the unroll shares the compiled step):
            # position i's length is lens + i + 1, exactly the
            # sequential decode's, so acceptance-matched positions
            # reduce bitwise-identically to the one-token path
            ctx = jnp.stack(
                [gqa_decode_attention(qh[:, i], kc, vc,
                                      lens + lv * (i + 1), tp=tp)
                 for i in range(w)], axis=1)       # [B, W, Hq, hd]
            return ctx.reshape(b, w, self.num_heads * hd), kc, vc

        ctx, kc, vc = apply_op(attend, q, k, v, kc0, vc0,
                               op_name="spec_attention")
        val = lambda t: t.value if isinstance(t, Tensor) else t  # noqa: E731
        return self._o_lora(ctx, lora), (val(kc), val(vc))

    def forward_decode_spec_paged(self, x, cos_full, sin_full, cache,
                                  page_table, lens, live, lora=None,
                                  tp=None):
        """Paged twin of :meth:`forward_decode_spec`: W per-row query
        positions over the shared page pool. Writes to dead rows,
        unmapped pages, or positions past the table width are DROPPED
        (the ``write_tokens`` sentinel convention), so a draft window
        reaching past a slot's grown coverage degrades to fewer
        accepted tokens instead of corrupting a neighbour's page."""
        b, w = x.shape[0], x.shape[1]
        hd = self.config.head_dim
        q, k, v = self._qkv_lora(x, lora)
        quant = len(cache) == 4   # (k, v, k_scale, v_scale) int8 pools

        def _prep(qv, kv, vv, kp):
            ps = kp.shape[1]
            max_len = page_table.shape[1] * ps
            pos = lens[:, None] + jnp.arange(w, dtype=jnp.int32)[None]
            idx = jnp.minimum(pos, max_len - 1)
            c = cos_full[idx][:, :, None, :]
            sn = sin_full[idx][:, :, None, :]
            qh = apply_rotary_emb(qv.reshape(b, w, self.num_heads, hd),
                                  c, sn)
            kh = apply_rotary_emb(kv.reshape(b, w, self.kv_heads, hd),
                                  c, sn)
            vh = vv.reshape(b, w, self.kv_heads, hd)
            ar = jnp.arange(b)
            page = page_table[ar[:, None], idx // ps]       # [B, W]
            ok = live[:, None] & (page >= 0) & (pos < max_len)
            page = jnp.where(ok, page, kp.shape[0])
            return qh, kh, vh, page, idx % ps

        def attend(qv, kv, vv, kp, vp):
            qh, kh, vh, page, offs = _prep(qv, kv, vv, kp)
            kp = kp.at[page, offs].set(kh.astype(kp.dtype),
                                       mode="drop")
            vp = vp.at[page, offs].set(vh.astype(vp.dtype),
                                       mode="drop")
            from ..ops.paged_attention import paged_decode_mha

            lv = live.astype(jnp.int32)
            ctx = jnp.stack(
                [paged_decode_mha(qh[:, i], kp, vp, page_table,
                                  lens + lv * (i + 1), tp=tp)
                 for i in range(w)], axis=1)
            return ctx.reshape(b, w, self.num_heads * hd), kp, vp

        def attend_q(qv, kv, vv, kp, vp, ks, vs):
            # int8 pools: the verify window must store-then-attend one
            # position at a time through the SAME running-absmax
            # primitive as single-token decode — a scale-growth event
            # at window row i requantizes the page before position
            # i+1's read, exactly as the sequential plain path would,
            # so acceptance-matched positions reduce bitwise to it.
            # The window rows are still PROVISIONAL (acceptance may
            # reject all but a prefix, and the plain path never writes
            # rejected rows — their absmax joining a page's MONOTONIC
            # running scale would be unrecoverable), so the touched
            # pages + scale tables snapshot BEFORE any store and ride
            # out as aux with the float rows: the engine restores the
            # snapshot post-acceptance and replays only the accepted
            # prefix (ContinuousBatchingEngine._commit_spec_rows).
            from ..ops.paged_attention import paged_decode_mha
            from ..quantization.kv import quant_store_rows

            qh, kh, vh, page, offs = _prep(qv, kv, vv, kp)
            safe = jnp.minimum(page.reshape(-1), kp.shape[0] - 1)
            snap_k, snap_v = kp[safe], vp[safe]
            snap_ks, snap_vs = ks, vs
            lv = live.astype(jnp.int32)
            ctxs = []
            for i in range(w):
                kp, ks = quant_store_rows(kp, ks, page[:, i],
                                          offs[:, i], kh[:, i])
                vp, vs = quant_store_rows(vp, vs, page[:, i],
                                          offs[:, i], vh[:, i])
                ctxs.append(paged_decode_mha(
                    qh[:, i], kp, vp, page_table,
                    lens + lv * (i + 1), ks, vs, tp=tp))
            ctx = jnp.stack(ctxs, axis=1)
            return (ctx.reshape(b, w, self.num_heads * hd), kp, vp,
                    ks, vs, snap_k, snap_v, snap_ks, snap_vs,
                    kh, vh, page, offs)

        val = lambda t: t.value if isinstance(t, Tensor) else t  # noqa: E731
        if quant:
            (ctx, kp, vp, ks, vs, snap_k, snap_v, snap_ks, snap_vs,
             kh, vh, page, offs) = apply_op(
                attend_q, q, k, v, *cache,
                op_name="spec_paged_attention")
            return (self._o_lora(ctx, lora),
                    (val(kp), val(vp), val(ks), val(vs)),
                    tuple(val(t) for t in
                          (snap_k, snap_v, snap_ks, snap_vs,
                           kh, vh, page, offs)))
        ctx, kp, vp = apply_op(attend, q, k, v, *cache,
                               op_name="spec_paged_attention")
        return self._o_lora(ctx, lora), (val(kp), val(vp)), None

    def forward_decode_paged(self, x, cos_full, sin_full, cache,
                             page_table, lens, live, lora=None,
                             tp=None):
        """Paged decode step: like forward_decode_ragged but the KV cache
        is this layer's slice of a shared page pool (ops/paged_attention
        + inference/paged_cache — the vLLM-style serving layout the
        reference's contiguous CacheKV slabs cannot express). Writes to
        dead rows and unmapped pages are DROPPED via an out-of-range
        sentinel, so the step stays one compiled program."""
        b = x.shape[0]
        hd = self.config.head_dim
        q, k, v = self._qkv_lora(x, lora)
        quant = len(cache) == 4   # (k, v, k_scale, v_scale) int8 pools

        def _prep(qv, kv, vv, kp):
            ps = kp.shape[1]
            idx = jnp.minimum(lens, page_table.shape[1] * ps - 1)
            c = cos_full[idx][:, None, None, :]
            sn = sin_full[idx][:, None, None, :]
            qh = apply_rotary_emb(
                qv.reshape(b, 1, self.num_heads, hd), c, sn)[:, 0]
            kh = apply_rotary_emb(
                kv.reshape(b, 1, self.kv_heads, hd), c, sn)[:, 0]
            vh = vv.reshape(b, self.kv_heads, hd)
            page = page_table[jnp.arange(b), idx // ps]
            # dead rows / unmapped pages -> sentinel, dropped by scatter
            page = jnp.where(live & (page >= 0), page, kp.shape[0])
            return qh, kh, vh, page, idx % ps

        def attend(qv, kv, vv, kp, vp):
            qh, kh, vh, page, offs = _prep(qv, kv, vv, kp)
            kp = kp.at[page, offs].set(kh.astype(kp.dtype),
                                       mode="drop")
            vp = vp.at[page, offs].set(vh.astype(vp.dtype),
                                       mode="drop")
            from ..ops.paged_attention import paged_decode_mha

            ctx = paged_decode_mha(qh, kp, vp, page_table,
                                   lens + live.astype(jnp.int32),
                                   tp=tp)
            return ctx.reshape(b, 1, self.num_heads * hd), kp, vp

        def attend_q(qv, kv, vv, kp, vp, ks, vs):
            # int8 pools: quantize-on-store (running absmax rides the
            # scale arrays), fused dequant in the read kernel — the
            # decode-step HBM read is int8, the whole point on
            # bandwidth-bound decode
            from ..ops.paged_attention import paged_decode_mha
            from ..quantization.kv import quant_store_rows

            qh, kh, vh, page, offs = _prep(qv, kv, vv, kp)
            kp, ks = quant_store_rows(kp, ks, page, offs, kh)
            vp, vs = quant_store_rows(vp, vs, page, offs, vh)
            ctx = paged_decode_mha(qh, kp, vp, page_table,
                                   lens + live.astype(jnp.int32),
                                   ks, vs, tp=tp)
            return (ctx.reshape(b, 1, self.num_heads * hd), kp, vp,
                    ks, vs)

        val = lambda t: t.value if isinstance(t, Tensor) else t  # noqa: E731
        if quant:
            ctx, kp, vp, ks, vs = apply_op(
                attend_q, q, k, v, *cache, op_name="paged_attention")
            return self._o_lora(ctx, lora), (val(kp), val(vp), val(ks),
                                             val(vs))
        ctx, kp, vp = apply_op(attend, q, k, v, *cache,
                               op_name="paged_attention")
        return self._o_lora(ctx, lora), (val(kp), val(vp))

    def forward(self, x, cos, sin, attn_mask=None):
        b = x.shape[0]
        s = x.shape[1]
        hd = self.config.head_dim
        q = self.q_proj(x)
        k = self.k_proj(x)
        v = self.v_proj(x)

        def prep(qv, kv, vv, cv, sv):
            # GQA stays grouped: the flash kernel selects shared KV heads in
            # its index maps (no jnp.repeat — a 65B config with 64 q-heads /
            # 8 kv-heads would otherwise pay 8x KV activation memory)
            qh = apply_rotary_emb(qv.reshape(b, s, self.num_heads, hd), cv, sv)
            kh = apply_rotary_emb(kv.reshape(b, s, self.kv_heads, hd), cv, sv)
            vh = vv.reshape(b, s, self.kv_heads, hd)
            return qh, kh, vh

        qh, kh, vh = apply_op(prep, q, k, v, cos, sin, op_name="qkv_rope")
        qh = constraint(qh, P("dp", None, "mp", None))
        kh = constraint(kh, P("dp", None, "mp", None))
        vh = constraint(vh, P("dp", None, "mp", None))
        if attn_mask is None:
            ctx, _ = F.flash_attention(qh, kh, vh, causal=True)
        else:
            ctx = F.scaled_dot_product_attention(
                qh, kh, vh, attn_mask=attn_mask, is_causal=True)
        ctx = apply_op(lambda c: c.reshape(b, s, self.num_heads * hd), ctx,
                       op_name="merge_heads")
        ctx = constraint(ctx, P("dp", None, "mp"))
        return self.o_proj(ctx)


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        h, i = config.hidden_size, config.intermediate_size
        self.gate_proj = ColumnParallelLinear(h, i, has_bias=False,
                                              gather_output=False)
        self.up_proj = ColumnParallelLinear(h, i, has_bias=False,
                                            gather_output=False)
        self.down_proj = RowParallelLinear(i, h, has_bias=False,
                                           input_is_parallel=True)

    def forward(self, x, lora=None):
        if lora is None:
            return self.down_proj(F.silu(self.gate_proj(x))
                                  * self.up_proj(x))
        g = _lora_add(x, self.gate_proj(x), lora, "gate")
        u = _lora_add(x, self.up_proj(x), lora, "up")
        h = F.silu(g) * u
        return _lora_add(h, self.down_proj(h), lora, "down")


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       epsilon=config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                epsilon=config.rms_norm_eps)

    def forward(self, x, cos, sin, attn_mask=None):
        x = x + self.self_attn(self.input_layernorm(x), cos, sin, attn_mask)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return constraint(x, P("dp", None, None))

    def forward_with_cache(self, x, cos_full, sin_full, cache, pos,
                           lora=None, tp=None):
        attn, cache = self.self_attn.forward_with_cache(
            self.input_layernorm(x), cos_full, sin_full, cache, pos,
            lora=lora, tp=tp)
        x = x + attn
        x = x + self.mlp(self.post_attention_layernorm(x), lora=lora)
        return x, cache

    def forward_decode_ragged(self, x, cos_full, sin_full, cache, lens,
                              live, lora=None, tp=None):
        attn, cache = self.self_attn.forward_decode_ragged(
            self.input_layernorm(x), cos_full, sin_full, cache, lens,
            live, lora=lora, tp=tp)
        x = x + attn
        x = x + self.mlp(self.post_attention_layernorm(x), lora=lora)
        return x, cache

    def forward_decode_paged(self, x, cos_full, sin_full, cache,
                             page_table, lens, live, lora=None,
                             tp=None):
        attn, cache = self.self_attn.forward_decode_paged(
            self.input_layernorm(x), cos_full, sin_full, cache,
            page_table, lens, live, lora=lora, tp=tp)
        x = x + attn
        x = x + self.mlp(self.post_attention_layernorm(x), lora=lora)
        return x, cache

    def forward_decode_spec(self, x, cos_full, sin_full, cache, lens,
                            live, lora=None, tp=None):
        attn, cache = self.self_attn.forward_decode_spec(
            self.input_layernorm(x), cos_full, sin_full, cache, lens,
            live, lora=lora, tp=tp)
        x = x + attn
        x = x + self.mlp(self.post_attention_layernorm(x), lora=lora)
        return x, cache

    def forward_decode_spec_paged(self, x, cos_full, sin_full, cache,
                                  page_table, lens, live, lora=None,
                                  tp=None):
        attn, cache, aux = self.self_attn.forward_decode_spec_paged(
            self.input_layernorm(x), cos_full, sin_full, cache,
            page_table, lens, live, lora=lora, tp=tp)
        x = x + attn
        x = x + self.mlp(self.post_attention_layernorm(x), lora=lora)
        return x, cache, aux


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                   config.hidden_size)
        from ..nn.layer.container import LayerList

        self.layers = LayerList([LlamaDecoderLayer(config)
                                 for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, input_ids, attn_mask=None):
        cfg = self.config
        x = self.embed_tokens(input_ids)
        x = constraint(x, P("dp", None, None))
        s = x.shape[1]
        cos, sin = _rope_cos_sin(s, cfg.head_dim, cfg.rope_theta,
                                 x.value.dtype if isinstance(x, Tensor) else x.dtype)
        for layer in self.layers:
            if cfg.recompute == "full" and self.training:
                from ..distributed.fleet.recompute import recompute

                x = recompute(layer, x, cos, sin, attn_mask)
            else:
                x = layer(x, cos, sin, attn_mask)
        return self.norm(x)

    def init_cache(self, batch_size: int, max_len: int):
        """Preallocated per-layer KV caches (≙ the reference's
        CacheKV tensors fed to fused_multi_transformer)."""
        import numpy as _np

        cfg = self.config
        dt = jnp.dtype(cfg.dtype) if cfg.dtype != "float32" else jnp.float32
        shape = (batch_size, max_len, cfg.kv_heads, cfg.head_dim)
        return [(jnp.zeros(shape, dt), jnp.zeros(shape, dt))
                for _ in range(cfg.num_hidden_layers)]

    def forward_with_cache(self, input_ids, caches, pos, lora=None,
                           tp=None):
        cfg = self.config
        x = self.embed_tokens(input_ids)
        max_len = caches[0][0].shape[1]
        cos_full, sin_full = _rope_cos_sin(
            max_len, cfg.head_dim, cfg.rope_theta,
            x.value.dtype if isinstance(x, Tensor) else x.dtype)
        new_caches = []
        for i, (layer, cache) in enumerate(zip(self.layers, caches)):
            x, cache = layer.forward_with_cache(
                x, cos_full, sin_full, cache, pos,
                lora=_lora_layer(lora, i), tp=tp)
            new_caches.append(cache)
        return self.norm(x), new_caches

    def forward_decode_ragged(self, input_ids, caches, lens, live,
                              lora=None, tp=None):
        cfg = self.config
        x = self.embed_tokens(input_ids)
        max_len = caches[0][0].shape[1]
        cos_full, sin_full = _rope_cos_sin(
            max_len, cfg.head_dim, cfg.rope_theta,
            x.value.dtype if isinstance(x, Tensor) else x.dtype)
        new_caches = []
        for i, (layer, cache) in enumerate(zip(self.layers, caches)):
            x, cache = layer.forward_decode_ragged(
                x, cos_full, sin_full, cache, lens, live,
                lora=_lora_layer(lora, i), tp=tp)
            new_caches.append(cache)
        return self.norm(x), new_caches

    def init_paged_cache(self, num_pages: int, page_size: int,
                         kv_dtype: str = "bf16"):
        """Per-layer page POOLS (shared-table layout: one page_table,
        inference/paged_cache.PageAllocator, serves every layer).

        ``kv_dtype="bf16"`` (default) stores pages in the model's
        configured cache dtype — the bitwise pre-quantization layout.
        ``"int8"`` returns 4-tuples ``(k, v, k_scale, v_scale)`` per
        layer: int8 pools plus per-(page, kv_head) f32 running-absmax
        scales (quantization.kv conventions) that every paged
        decode/spec forward quantizes against on store and dequantizes
        with inside the attention kernel."""
        cfg = self.config
        shape = (num_pages, page_size, cfg.kv_heads, cfg.head_dim)
        if kv_dtype == "int8":
            from ..quantization.kv import KV_SCALE_FLOOR

            sshape = (num_pages, cfg.kv_heads)
            return [(jnp.zeros(shape, jnp.int8),
                     jnp.zeros(shape, jnp.int8),
                     jnp.full(sshape, KV_SCALE_FLOOR, jnp.float32),
                     jnp.full(sshape, KV_SCALE_FLOOR, jnp.float32))
                    for _ in range(cfg.num_hidden_layers)]
        dt = jnp.dtype(cfg.dtype) if cfg.dtype != "float32" else jnp.float32
        return [(jnp.zeros(shape, dt), jnp.zeros(shape, dt))
                for _ in range(cfg.num_hidden_layers)]

    def forward_decode_paged(self, input_ids, caches, page_table, lens,
                             live, lora=None, tp=None):
        cfg = self.config
        x = self.embed_tokens(input_ids)
        max_len = page_table.shape[1] * caches[0][0].shape[1]
        cos_full, sin_full = _rope_cos_sin(
            max_len, cfg.head_dim, cfg.rope_theta,
            x.value.dtype if isinstance(x, Tensor) else x.dtype)
        new_caches = []
        for i, (layer, cache) in enumerate(zip(self.layers, caches)):
            x, cache = layer.forward_decode_paged(
                x, cos_full, sin_full, cache, page_table, lens, live,
                lora=_lora_layer(lora, i), tp=tp)
            new_caches.append(cache)
        return self.norm(x), new_caches

    def forward_decode_spec(self, input_ids, caches, lens, live,
                            lora=None, tp=None):
        """Speculative verify step (dense ragged cache): input_ids
        [B, W] at per-row offsets ``lens`` — see
        LlamaAttention.forward_decode_spec."""
        cfg = self.config
        x = self.embed_tokens(input_ids)
        max_len = caches[0][0].shape[1]
        cos_full, sin_full = _rope_cos_sin(
            max_len, cfg.head_dim, cfg.rope_theta,
            x.value.dtype if isinstance(x, Tensor) else x.dtype)
        new_caches = []
        for i, (layer, cache) in enumerate(zip(self.layers, caches)):
            x, cache = layer.forward_decode_spec(
                x, cos_full, sin_full, cache, lens, live,
                lora=_lora_layer(lora, i), tp=tp)
            new_caches.append(cache)
        return self.norm(x), new_caches

    def forward_decode_spec_paged(self, input_ids, caches, page_table,
                                  lens, live, lora=None, tp=None):
        """Speculative verify step over the page pool — see
        LlamaAttention.forward_decode_spec_paged. The third result is
        the per-layer window-write aux (int8 pools: the float K/V rows
        + their page/offset targets, for the engine's post-acceptance
        running-absmax commit; ``None`` entries on bf16 pools)."""
        cfg = self.config
        x = self.embed_tokens(input_ids)
        max_len = page_table.shape[1] * caches[0][0].shape[1]
        cos_full, sin_full = _rope_cos_sin(
            max_len, cfg.head_dim, cfg.rope_theta,
            x.value.dtype if isinstance(x, Tensor) else x.dtype)
        new_caches = []
        aux_rows = []
        for i, (layer, cache) in enumerate(zip(self.layers, caches)):
            x, cache, aux = layer.forward_decode_spec_paged(
                x, cos_full, sin_full, cache, page_table, lens, live,
                lora=_lora_layer(lora, i), tp=tp)
            new_caches.append(cache)
            aux_rows.append(aux)
        return self.norm(x), new_caches, aux_rows


class LlamaForCausalLM(Layer):
    IGNORE_INDEX = -100

    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        from ..core.dtype import get_default_dtype, set_default_dtype

        prev = get_default_dtype()
        set_default_dtype(config.dtype)  # params honor the config dtype
        try:
            self.model = LlamaModel(config)
            if config.tie_word_embeddings:
                self.lm_head = None
            else:
                self.lm_head = ColumnParallelLinear(
                    config.hidden_size, config.vocab_size, has_bias=False,
                    gather_output=False)
        finally:
            set_default_dtype(prev)
        self.loss_fn = ParallelCrossEntropy(ignore_index=self.IGNORE_INDEX)

    def logits(self, hidden):
        if self.lm_head is None:
            w = self.model.embed_tokens.weight
            return apply_op(lambda hv, wv: hv @ wv.T, hidden, w,
                            op_name="tied_lm_head")
        return self.lm_head(hidden)

    def forward(self, input_ids, labels=None, attn_mask=None):
        hidden = self.model(input_ids, attn_mask)
        logits = self.logits(hidden)
        if labels is None:
            return logits
        loss = self.loss_fn(logits, labels)
        from ._utils import masked_lm_loss

        return masked_lm_loss(loss, labels, self.IGNORE_INDEX)

    def init_cache(self, batch_size: int, max_len: int):
        return self.model.init_cache(batch_size, max_len)

    def lora_shapes(self, targets):
        """LoRA bank geometry hook for the serving engines: returns
        ``(num_layers, {target: (d_in, d_out)})`` for the requested
        target projections (subset of q/k/v/o, gate/up/down). The
        engine stacks every resident adapter's factors into
        ``[L, K+1, r, d_in]`` / ``[L, K+1, d_out, r]`` device arrays
        per target and gathers each slot's delta inside the compiled
        decode programs (see :func:`_lora_add`)."""
        cfg = self.config
        hd = cfg.head_dim
        dims = {
            "q": (cfg.hidden_size, cfg.num_attention_heads * hd),
            "k": (cfg.hidden_size, cfg.kv_heads * hd),
            "v": (cfg.hidden_size, cfg.kv_heads * hd),
            "o": (cfg.num_attention_heads * hd, cfg.hidden_size),
            "gate": (cfg.hidden_size, cfg.intermediate_size),
            "up": (cfg.hidden_size, cfg.intermediate_size),
            "down": (cfg.intermediate_size, cfg.hidden_size),
        }
        unknown = [t for t in targets if t not in dims]
        if unknown:
            raise ValueError(
                f"unknown lora target(s) {unknown}; supported: "
                f"{sorted(dims)}")
        return cfg.num_hidden_layers, {t: dims[t] for t in targets}

    def forward_with_cache(self, input_ids, caches, pos, lora=None,
                           tp=None):
        """(logits_of_last_positions, new_caches) — the serving forward.
        ``lora`` (every serving forward below too) is the optional
        batched-adapter input ``(bank, adapter_idx)`` —
        see :func:`_lora_add`."""
        hidden, caches = self.model.forward_with_cache(
            input_ids, caches, pos, lora=lora, tp=tp)
        return self.logits(hidden), caches

    def forward_decode_ragged(self, input_ids, caches, lens, live,
                              lora=None, tp=None):
        """(logits [B, 1, V], new_caches) — the mixed-length decode step
        (per-row positions; see LlamaAttention.forward_decode_ragged)."""
        hidden, caches = self.model.forward_decode_ragged(
            input_ids, caches, lens, live, lora=lora, tp=tp)
        return self.logits(hidden), caches

    def init_paged_cache(self, num_pages: int, page_size: int,
                         kv_dtype: str = "bf16"):
        return self.model.init_paged_cache(num_pages, page_size,
                                           kv_dtype=kv_dtype)

    def forward_decode_paged(self, input_ids, caches, page_table, lens,
                             live, lora=None, tp=None):
        """(logits [B, 1, V], new_caches) — paged decode step (page-pool
        KV; see LlamaAttention.forward_decode_paged)."""
        hidden, caches = self.model.forward_decode_paged(
            input_ids, caches, page_table, lens, live, lora=lora,
            tp=tp)
        return self.logits(hidden), caches

    def forward_decode_spec(self, input_ids, caches, lens, live,
                            lora=None, tp=None):
        """(logits [B, W, V], new_caches) — batched speculative verify
        step at per-row offsets (dense ragged cache)."""
        hidden, caches = self.model.forward_decode_spec(
            input_ids, caches, lens, live, lora=lora, tp=tp)
        return self.logits(hidden), caches

    def forward_decode_spec_paged(self, input_ids, caches, page_table,
                                  lens, live, lora=None, tp=None):
        """(logits [B, W, V], new_caches, aux) — batched speculative
        verify step over the page pool; ``aux`` is the per-layer
        window-write rows for the engine's post-acceptance int8 commit
        (``None`` entries on bf16 pools)."""
        hidden, caches, aux = self.model.forward_decode_spec_paged(
            input_ids, caches, page_table, lens, live, lora=lora,
            tp=tp)
        return self.logits(hidden), caches, aux
