"""Shared model helpers."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.autograd import apply_op

IGNORE_INDEX = -100


def masked_lm_loss(loss, labels, ignore_index: int = IGNORE_INDEX):
    """Mean of per-token losses over NON-ignored positions only (ignored
    positions contribute 0 to the sum; dividing by the total count would
    scale the loss with the pad fraction)."""

    def masked_mean(l, lb):
        n = jnp.maximum(jnp.sum(lb != ignore_index), 1)
        return jnp.sum(l) / n.astype(l.dtype)

    return apply_op(masked_mean, loss, labels, op_name="lm_loss_mean")
