"""Paged decode attention: KV cache as a shared page pool.

Reference analog: the fused_multi_transformer decode path
(paddle/phi/kernels/fusion/fused_multi_transformer_op.cu.h:745 masked
MHA over a per-batch cache slab). The reference allocates each
sequence's cache contiguously at ``max_len``; THIS module completes the
SURVEY §7 hard part ("KV-cache decode kernel with paged/ragged
batching"): cache pages of ``page_size`` tokens live in one shared pool
``[num_pages, page_size, H, D]`` and a sequence's cache is the page-id
row of a ``page_table`` — so HBM holds the tokens actually in flight
(rounded up to pages), not ``max_batch * max_len``, and admission never
fails on fragmentation (any free page serves any slot).

TPU-native mechanism: the page table rides Pallas SCALAR PREFETCH
(``pltpu.PrefetchScalarGridSpec``) — block index maps read the
prefetched table to aim each K/V page DMA, which is the idiomatic TPU
form of paged attention (indirect addressing happens at DMA-issue time,
not as a gather in the kernel body). The softmax math is byte-for-byte
the ragged ``decode_mha`` recurrence (pallas_kernels.py): online
softmax over pages, block-skip past each row's length, so a short row
costs O(its length).

``PagedKVCache`` (inference/paged_cache.py) owns the pool + free-list;
this module is the pure compute.

Relationship to ``ops/pallas.py::paged_attention``: that function wraps
the STOCK ``jax.experimental.pallas.ops.tpu.paged_attention`` kernel
(same pool/page-table layout) and is the TPU-only, tuned option; THIS
kernel is the framework's own from-scratch implementation — it also
runs in interpret mode (CPU tests) and is the one the parity suite and
PagedKVCache exercise. Numerics agree; fixes to the page-table
convention (-1 unmapped, clamp-on-skip) must land in both.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

__all__ = ["paged_decode_mha"]


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _paged_decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, m_ref, l_ref, *, scale, page_size):
    """One (batch row, page) step of the online-softmax recurrence.

    ``pt_ref``/``len_ref`` are scalar-prefetched; the K/V blocks arriving
    here were already DMA'd from the page the index map selected."""
    ib, jp = pl.program_id(0), pl.program_id(1)
    npg = pl.num_programs(1)

    @pl.when(jp == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    ln = len_ref[ib]

    # skip pages entirely past the valid length (same contract as
    # decode_mha: short rows cost O(their length))
    @pl.when(jp * page_size < ln)
    def _compute():
        q = q_ref[0].astype(jnp.float32)            # [Hq, D]
        k = k_ref[0].astype(jnp.float32)            # [ps, Hkv, D]
        v = v_ref[0].astype(jnp.float32)
        g = q.shape[0] // k.shape[1]
        if g > 1:                                   # GQA: share KV heads
            k = jnp.repeat(k, g, axis=1)            # VMEM-local repeat
            v = jnp.repeat(v, g, axis=1)
        s = jnp.sum(q[None] * k, axis=-1) * scale   # [ps, Hq]
        pos = jp * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (page_size, 1), 0)
        mask = pos < ln                             # [ps, 1]
        s = jnp.where(mask, s, -1e30)
        m_prev = m_ref[...]                         # [1, H]
        m_cur = jnp.max(s, axis=0, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                      # [ps, H]
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)             # [1, H]
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=0, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = (acc_ref[...] * jnp.transpose(alpha)
                        + jnp.sum(p[:, :, None] * v, axis=0))  # [H, D]

    @pl.when(jp == npg - 1)
    def _finalize():
        l_safe = jnp.maximum(jnp.transpose(l_ref[...]), 1e-30)  # [H, 1]
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_mha(q, k_pool, v_pool, page_table, seq_lens,
                     interpret=None):
    """Single-step decode attention over a paged KV pool.

    q: [B, Hq, D] (this step's query)
    k_pool/v_pool: [num_pages, page_size, Hkv, D] shared pools (GQA:
        Hq may be a multiple of Hkv — KV heads are shared in-kernel)
    page_table: [B, max_pages] int32 — page ids per sequence, in order;
        entries past a row's length are never dereferenced (clamped to 0
        for the skipped DMA)
    seq_lens: [B] int32 valid lengths (the new token's k/v must already
        be written at position seq_lens-1 via PagedKVCache.write_tokens)
    Returns [B, H, D].
    """
    if pltpu is None:
        # the grid spec below needs jax.experimental.pallas.tpu even in
        # interpret mode; without it the failure would be an opaque
        # AttributeError on the None module
        raise NotImplementedError(
            "paged_decode_mha requires jax.experimental.pallas.tpu "
            "(scalar-prefetch grid spec), which this jax build does not "
            "provide — install a jax with TPU Pallas support (the CPU "
            "interpret path uses the same grid spec)")
    b, h, d = q.shape
    hkv = k_pool.shape[2]
    if h % hkv:
        raise ValueError(f"Hq={h} not a multiple of Hkv={hkv}")
    page_size = k_pool.shape[1]
    npages = page_table.shape[1]
    scale = 1.0 / math.sqrt(d)
    it = _interpret() if interpret is None else interpret

    def _page(bi, pi, pt, _lens):
        # clamp: skipped steps (page beyond seq_len, table entry -1)
        # still issue a DMA — aim it at page 0 harmlessly
        return (jnp.maximum(pt[bi, pi], 0), 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, npages),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda bi, pi, pt, ln: (bi, 0, 0)),
            pl.BlockSpec((1, page_size, hkv, d), _page),
            pl.BlockSpec((1, page_size, hkv, d), _page),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda bi, pi, pt, ln: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),
            pltpu.VMEM((1, h), jnp.float32),
            pltpu.VMEM((1, h), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_decode_kernel, scale=scale,
                          page_size=page_size),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid_spec=grid_spec,
        interpret=it,
    )(page_table, seq_lens, q, k_pool, v_pool)
