"""Paged decode attention: KV cache as a shared page pool.

Reference analog: the fused_multi_transformer decode path
(paddle/phi/kernels/fusion/fused_multi_transformer_op.cu.h:745 masked
MHA over a per-batch cache slab). The reference allocates each
sequence's cache contiguously at ``max_len``; THIS module completes the
SURVEY §7 hard part ("KV-cache decode kernel with paged/ragged
batching"): cache pages of ``page_size`` tokens live in one shared pool
``[num_pages, page_size, H, D]`` and a sequence's cache is the page-id
row of a ``page_table`` — so HBM holds the tokens actually in flight
(rounded up to pages), not ``max_batch * max_len``, and admission never
fails on fragmentation (any free page serves any slot).

TPU-native mechanism: the page table rides Pallas SCALAR PREFETCH
(``pltpu.PrefetchScalarGridSpec``) — block index maps read the
prefetched table to aim each K/V page DMA, which is the idiomatic TPU
form of paged attention (indirect addressing happens at DMA-issue time,
not as a gather in the kernel body). The softmax math is byte-for-byte
the ragged ``decode_mha`` recurrence (pallas_kernels.py): online
softmax over pages, block-skip past each row's length, so a short row
costs O(its length).

``PagedKVCache`` (inference/paged_cache.py) owns the pool + free-list;
this module is the pure compute.

QUANTIZED pools (``kv_dtype="int8"`` serving): pass the per-(page,
kv_head) absmax scale arrays and the kernel dequantizes AFTER the page
DMA (``paddle_tpu.quantization.kv`` conventions) — decode's HBM read
is half the bytes, which is the whole lever on bandwidth-bound decode.
A jax build without ``jax.experimental.pallas.tpu`` (the grid spec
needs it even in interpret mode) falls back to a pure-jnp dense-gather
reference with the same math — CPU-compat, not a performance path.

Relationship to ``ops/pallas.py::paged_attention``: that function wraps
the STOCK ``jax.experimental.pallas.ops.tpu.paged_attention`` kernel
(same pool/page-table layout) and is the TPU-only, tuned option; THIS
kernel is the framework's own from-scratch implementation — it also
runs in interpret mode (CPU tests) and is the one the parity suite and
PagedKVCache exercise. Numerics agree; fixes to the page-table
convention (-1 unmapped, clamp-on-skip) must land in both.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from ..quantization.kv import KV_QMAX as _KV_QMAX

__all__ = ["paged_decode_mha"]


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _paged_decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, m_ref, l_ref, *, scale, page_size,
                         ks_ref=None, vs_ref=None):
    """One (batch row, page) step of the online-softmax recurrence.

    ``pt_ref``/``len_ref`` are scalar-prefetched; the K/V blocks arriving
    here were already DMA'd from the page the index map selected. With
    ``ks_ref``/``vs_ref`` bound (int8 pools) the K/V block is int8 and
    the per-(page, kv_head) absmax scales dequantize it HERE, after the
    DMA — the HBM read is half the bytes, which is the whole point on
    bandwidth-bound decode."""
    ib, jp = pl.program_id(0), pl.program_id(1)
    npg = pl.num_programs(1)

    @pl.when(jp == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    ln = len_ref[ib]

    # skip pages entirely past the valid length (same contract as
    # decode_mha: short rows cost O(their length))
    @pl.when(jp * page_size < ln)
    def _compute():
        q = q_ref[0].astype(jnp.float32)            # [Hq, D]
        k = k_ref[0].astype(jnp.float32)            # [ps, Hkv, D]
        v = v_ref[0].astype(jnp.float32)
        if ks_ref is not None:
            # fused dequant (quantization.kv conventions): the scale
            # block is this page's [Hkv] absmax row, selected by the
            # same prefetched-table index map that aimed the K/V DMA
            k = k * (ks_ref[0] * (1.0 / _KV_QMAX))[None, :, None]
            v = v * (vs_ref[0] * (1.0 / _KV_QMAX))[None, :, None]
        g = q.shape[0] // k.shape[1]
        if g > 1:                                   # GQA: share KV heads
            k = jnp.repeat(k, g, axis=1)            # VMEM-local repeat
            v = jnp.repeat(v, g, axis=1)
        s = jnp.sum(q[None] * k, axis=-1) * scale   # [ps, Hq]
        pos = jp * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (page_size, 1), 0)
        mask = pos < ln                             # [ps, 1]
        s = jnp.where(mask, s, -1e30)
        m_prev = m_ref[...]                         # [1, H]
        m_cur = jnp.max(s, axis=0, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                      # [ps, H]
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)             # [1, H]
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=0, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = (acc_ref[...] * jnp.transpose(alpha)
                        + jnp.sum(p[:, :, None] * v, axis=0))  # [H, D]

    @pl.when(jp == npg - 1)
    def _finalize():
        l_safe = jnp.maximum(jnp.transpose(l_ref[...]), 1e-30)  # [H, 1]
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def _paged_decode_ref(q, k_pool, v_pool, page_table, seq_lens,
                      k_scale=None, v_scale=None):
    """Pure-jnp reference/fallback: gather each row's pages dense and
    run a masked softmax. Used when this jax build lacks
    ``jax.experimental.pallas.tpu`` (the grid spec below needs it even
    in interpret mode) — numerically equivalent to the kernel (same
    f32 math, plain instead of online softmax), NOT byte-identical,
    and it materializes [B, max_pages*page_size] KV so it is a
    CPU-compat path, not a performance one. Quantized pools dequant
    here with the same ``quantization.kv`` conventions the fused
    kernel uses."""
    b, h, d = q.shape
    hkv = k_pool.shape[2]
    ps = k_pool.shape[1]
    idx = jnp.maximum(page_table, 0)                 # [B, maxp]
    k = k_pool[idx].astype(jnp.float32)              # [B, maxp, ps, Hkv, D]
    v = v_pool[idx].astype(jnp.float32)
    if k_scale is not None:
        k = k * (k_scale[idx] / _KV_QMAX)[:, :, None, :, None]
        v = v * (v_scale[idx] / _KV_QMAX)[:, :, None, :, None]
    L = idx.shape[1] * ps
    k = k.reshape(b, L, hkv, d)
    v = v.reshape(b, L, hkv, d)
    g = h // hkv
    if g > 1:
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
    s = jnp.einsum("bhd,blhd->blh", q.astype(jnp.float32), k)
    s = s * (1.0 / math.sqrt(d))
    mask = (jnp.arange(L, dtype=jnp.int32)[None, :, None]
            < seq_lens[:, None, None])
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=1)
    p = jnp.where(mask, p, 0.0)
    return jnp.einsum("blh,blhd->bhd", p, v).astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("interpret", "tp"))
def paged_decode_mha(q, k_pool, v_pool, page_table, seq_lens,
                     k_scale=None, v_scale=None, interpret=None,
                     tp=None):
    """Single-step decode attention over a paged KV pool.

    q: [B, Hq, D] (this step's query)
    k_pool/v_pool: [num_pages, page_size, Hkv, D] shared pools (GQA:
        Hq may be a multiple of Hkv — KV heads are shared in-kernel)
    page_table: [B, max_pages] int32 — page ids per sequence, in order;
        entries past a row's length are never dereferenced (clamped to 0
        for the skipped DMA)
    seq_lens: [B] int32 valid lengths (the new token's k/v must already
        be written at position seq_lens-1 via PagedKVCache.write_tokens)
    k_scale/v_scale: [num_pages, Hkv] f32 per-page-per-head absmax
        scales for INT8 pools (quantization.kv conventions) — pass both
        or neither. Dequant fuses into the kernel after the page DMA,
        so the HBM read stays int8 (the bandwidth win quantized KV
        exists for); the output is f32-accumulated either way.
    tp: tensor-parallel handle ``(mesh, axis)`` (static) — wraps the
        kernel in ``shard_map`` over the head axis: q shards on Hq,
        pools (and scales) on Hkv, table/lens replicate, and each mesh
        shard runs the UNMODIFIED kernel on its local head slice (pages
        are never split, so the page-table indirection is per-shard
        identical). Zero communication inside attention; on TPU this is
        what keeps the sharded pools' HBM win real — without it the
        Mosaic custom call would force an all-gather of the pool every
        decode step.
    Returns [B, H, D].
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale or neither")
    if tp is not None:
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        mesh, ax = tp
        head, pool, sc = (P(None, ax, None), P(None, None, ax, None),
                          P(None, ax))
        operands = [q, k_pool, v_pool, page_table, seq_lens]
        in_specs = [head, pool, pool, P(), P()]
        if k_scale is not None:
            operands += [k_scale, v_scale]
            in_specs += [sc, sc]
        return shard_map(
            lambda *a: paged_decode_mha(*a, interpret=interpret),
            mesh=mesh, in_specs=tuple(in_specs), out_specs=head,
            check_rep=False)(*operands)
    if pltpu is None:
        # the scalar-prefetch grid spec needs jax.experimental.pallas
        # .tpu even in interpret mode — fall back to the dense-gather
        # reference (same math, no paging win) instead of failing
        return _paged_decode_ref(q, k_pool, v_pool, page_table,
                                 seq_lens, k_scale, v_scale)
    b, h, d = q.shape
    hkv = k_pool.shape[2]
    if h % hkv:
        raise ValueError(f"Hq={h} not a multiple of Hkv={hkv}")
    page_size = k_pool.shape[1]
    npages = page_table.shape[1]
    scale = 1.0 / math.sqrt(d)
    it = _interpret() if interpret is None else interpret

    def _page(bi, pi, pt, _lens):
        # clamp: skipped steps (page beyond seq_len, table entry -1)
        # still issue a DMA — aim it at page 0 harmlessly
        return (jnp.maximum(pt[bi, pi], 0), 0, 0, 0)

    def _page_scale(bi, pi, pt, _lens):
        return (jnp.maximum(pt[bi, pi], 0), 0)

    quant = k_scale is not None
    in_specs = [
        pl.BlockSpec((1, h, d), lambda bi, pi, pt, ln: (bi, 0, 0)),
        pl.BlockSpec((1, page_size, hkv, d), _page),
        pl.BlockSpec((1, page_size, hkv, d), _page),
    ]
    operands = [q, k_pool, v_pool]
    if quant:
        in_specs += [pl.BlockSpec((1, hkv), _page_scale),
                     pl.BlockSpec((1, hkv), _page_scale)]
        operands += [k_scale, v_scale]

    def kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, *rest):
        if quant:
            ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
        else:
            ks_ref = vs_ref = None
            o_ref, acc_ref, m_ref, l_ref = rest
        _paged_decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref,
                             o_ref, acc_ref, m_ref, l_ref, scale=scale,
                             page_size=page_size, ks_ref=ks_ref,
                             vs_ref=vs_ref)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, npages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, d), lambda bi, pi, pt, ln: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),
            pltpu.VMEM((1, h), jnp.float32),
            pltpu.VMEM((1, h), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid_spec=grid_spec,
        interpret=it,
    )(page_table, seq_lens, q, *operands[1:])
