"""Op-definition helpers: differentiable vs non-differentiable wrappers."""
from __future__ import annotations

import functools

from ..core.autograd import apply_op, no_grad
from ..core.tensor import Tensor


def diff_op(fn, name=None):
    """Wrap a pure jax fn as a differentiable eager op."""

    n = name or getattr(fn, "__name__", "op")

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        return apply_op(fn, *args, op_name=n, **kwargs)

    wrapped.__name__ = n
    return wrapped


def nondiff_op(fn, name=None):
    """Wrap a jax fn whose outputs never carry gradient (comparisons, argmax...)."""

    n = name or getattr(fn, "__name__", "op")

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with no_grad():
            return apply_op(fn, *args, op_name=n, **kwargs)

    wrapped.__name__ = n
    return wrapped


def unwrap(x):
    return x._value if isinstance(x, Tensor) else x
