"""Head-batched flash attention: native ``[B, S, H, D]`` layout.

The BHSD kernel (flash_attention_kernel.py) forces BSHD->BHSD transposes
around every attention call — ~11ms/step of pure HBM relayout at the
350M bench shapes (PERF.md). A per-head BSHD block (1, bq, 1, D) is
illegal on TPU (the H dim breaks the (8,128) tiling), but a HEAD-BATCHED
block (1, bq, H, D) is legal: the last two dims are (H, D) = (8, 128).
This kernel processes ALL heads per grid step:

- scores are a statically-unrolled Python loop of per-head 2D dots over
  ``[:, i, :]`` slices of the native block, stacked to (H, bq, bk) in
  VMEM (the original H-batched 3D ``dot_general`` was Mosaic-rejected
  on-chip 2026-07-31 — "Bad lhs type"; see ``_per_head``),
- online-softmax stats are (H, bq, 1),
- the grid drops the head dimension: (B, nq, nk) — H x fewer grid steps.

VMEM bounds the block size: scores+probs at fp32 are 2·H·bq·bk·4 bytes
(8MB at H=8, bq=bk=512), so default blocks are 512 here vs 1024 for the
per-head kernel. Whether the transpose savings beat the smaller blocks is
an EMPIRICAL question — `experiments/exp_flash_hb.py` measures it; the
router (ops/pallas.py) keeps this path opt-in via FLAGS_flash_head_batched
until the TPU numbers say otherwise.

Scope: Hq == Hkv (the bench config), dropout-free. GQA/dropout route to
the per-head kernel.
"""
from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention_kernel import (_NEG_INF, _VMEM, _apply_causal_mask,
                                     _interpret, _pick_block)

__all__ = ["flash_attention_bshd_hb", "supports_hb"]

# scores+probs live in VMEM at fp32: 2 * H * bq * bk * 4 bytes must fit
# alongside q/k/v blocks and the fp32 accumulators (~16MB VMEM/core)
_VMEM_SCORE_BUDGET = 16 << 20


def supports_hb(q_shape, k_shape, dropout_p: float,
                interpret: Optional[bool] = None,
                block: int = 512) -> bool:
    b, sq, h, d = q_shape
    hkv, sk = k_shape[2], k_shape[1]
    it = _interpret() if interpret is None else interpret
    # 2026-07-31 on-chip finding (experiments/tpu_session.log): Mosaic on
    # the v5e toolchain rejected the H-batched 3D tpu.matmul the original
    # kernel was built around ("Bad lhs type", remote_compile 500) at
    # every block size tried.  The kernel has since been restructured to
    # statically-unrolled per-head 2D dots (whose slice/store forms are
    # themselves unverified on hardware — see _per_head), so device
    # routing stays off until PADDLE_TPU_HB_ON_DEVICE=1 — set by the
    # session script's on-chip test step (tpu_session.sh step 1; note
    # exp_flash_hb calls the kernel DIRECTLY and never consults this
    # gate) — verifies it; flip the default only after a measured win.
    # Per-head (6.0 ms fwd+bwd at bench shapes) remains the device path.
    if not it and os.environ.get("PADDLE_TPU_HB_ON_DEVICE", "") != "1":
        return False
    # this kernel does bf16 D-contracting dots WITHOUT the _sublane_plan
    # padding the per-head kernels apply — at D % 128 != 0 Mosaic would
    # reject them ("Bad lhs type"), so refuse device routing there (the
    # per-head path handles those shapes natively via its pad plan)
    if not it and d % 128 != 0:
        return False
    return (h == hkv and dropout_p == 0.0
            and 2 * h * block * block * 4 <= _VMEM_SCORE_BUDGET
            and _pick_block(sq, block, it) is not None
            and _pick_block(sk, block, it) is not None)


def _dot2d(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


def _per_head(fn, h):
    """Static Python loop over heads, stacked to (H, ...): Mosaic on the
    v5e toolchain rejects H-batched 3D tpu.matmul ("Bad lhs type",
    2026-07-31 on-chip).  The replacement 2D dot forms match the per-head
    kernel's on-chip-proven dots; the per-head STATIC slices of the
    native (bq, H, D) block ([:, i, :] — no transposes, no materialized
    head-leading copies) are themselves unverified on hardware until the
    session script's on-chip test step runs.  H is a trace-time constant,
    so this unrolls — kernel code size grows H×, MXU work is
    identical."""
    return jnp.stack([fn(i) for i in range(h)], 0)


def _scores_hb(q, k, sm_scale, causal, iq, ik, bq, bk, offset):
    """(H, bq, bk) fp32 scores; masking shared with the per-head kernel
    (_apply_causal_mask) so the alignment convention cannot diverge.
    ``q``/``k`` arrive in the NATIVE block layout (bq|bk, H, D); heads
    are sliced statically, one 2D NT dot each."""
    h = q.shape[1]
    s = _per_head(
        lambda i: _dot2d(q[:, i, :], k[:, i, :], ((1,), (1,))), h) \
        * sm_scale
    return _apply_causal_mask(s, causal, iq, ik, bq, bk, offset,
                              lead_batch=True)


def _fwd_kernel_hb(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                   l_ref, *, sm_scale, causal, offset, bq, bk):
    b, iq, ik = (pl.program_id(i) for i in range(3))
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _compute():
        q = q_ref[0]                          # (bq, H, D) native layout
        k = k_ref[0]                          # (bk, H, D)
        v = v_ref[0]
        h = q.shape[1]
        s, valid = _scores_hb(q, k, sm_scale, causal, iq, ik, bq, bk,
                              offset)         # (H, bq, bk)
        m_prev = m_ref[:, :, 0:1]             # (H, bq, 1)
        l_prev = l_ref[:, :, 0:1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        if valid is not None:
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:, :, 0:1] = l_prev * alpha + jnp.sum(p, -1, keepdims=True)
        # per-head P_h @ V_h: (bq, bk) x (bk, D) -> stacked (H, bq, D)
        pv = _per_head(
            lambda i: _dot2d(p[i].astype(v.dtype), v[:, i, :],
                             ((1,), (0,))), h)
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[:, :, 0:1] = m_new

    if causal:
        needed = ik * bk <= iq * bq + bq - 1 + offset
        pl.when(needed)(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_ref[:, :, 0:1]
        l_safe = jnp.maximum(l, 1e-30)
        for i in range(acc_ref.shape[0]):     # per-head static stores —
            o_ref[0, :, i, :] = (acc_ref[i] / l_safe[i]).astype(
                o_ref.dtype)                  # no (H,bq,D) transpose
        lse_ref[0] = (m_ref[:, :, 0:1] + jnp.log(l_safe))[:, :, 0]


def _fwd_impl_hb(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    bsz, sq, h, d = q.shape
    sk = k.shape[1]
    bq = _pick_block(sq, block_q, interpret)
    bk = _pick_block(sk, block_k, interpret)
    nq, nk = sq // bq, sk // bk
    offset = sk - sq
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_hb, sm_scale=sm_scale, causal=causal,
                          offset=offset, bq=bq, bk=bk),
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((bsz, h, sq), jnp.float32)],
        grid=(bsz, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, h, d), lambda b, i, j: (b, i, 0, 0)),
            pl.BlockSpec((1, bk, h, d), lambda b, i, j: (b, j, 0, 0)),
            pl.BlockSpec((1, bk, h, d), lambda b, i, j: (b, j, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, h, d), lambda b, i, j: (b, i, 0, 0)),
            pl.BlockSpec((1, h, bq), lambda b, i, j: (b, 0, i)),
        ],
        scratch_shapes=[
            _VMEM((h, bq, d), jnp.float32),
            _VMEM((h, bq, 128), jnp.float32),
            _VMEM((h, bq, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _bwd_dq_kernel_hb(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, acc_ref, *, sm_scale, causal, offset, bq, bk):
    b, iq, ik = (pl.program_id(i) for i in range(3))
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[0]                                  # (bq, H, D) native
        k = k_ref[0]                                  # (bk, H, D)
        v = v_ref[0]
        do = do_ref[0]                                # (bq, H, D)
        h = q.shape[1]
        lse = lse_ref[0][:, :, None]                  # (H, bq, 1)
        delta = delta_ref[0][:, :, None]
        s, valid = _scores_hb(q, k, sm_scale, causal, iq, ik, bq, bk,
                              offset)
        p = jnp.exp(s - lse)
        if causal and offset < 0:
            p = jnp.where(valid, p, 0.0)
        # per-head dP_h = dO_h @ V_h^T: (bq, D) x (bk, D) -> (H, bq, bk)
        dpd = _per_head(
            lambda i: _dot2d(do[:, i, :], v[:, i, :], ((1,), (1,))), h)
        ds = p * (dpd - delta)
        # per-head dQ_h += dS_h @ K_h: (bq, bk) x (bk, D) -> (H, bq, D)
        acc_ref[...] += _per_head(
            lambda i: _dot2d(ds[i].astype(k.dtype), k[:, i, :],
                             ((1,), (0,))), h) * sm_scale

    if causal:
        needed = ik * bk <= iq * bq + bq - 1 + offset
        pl.when(needed)(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _finalize():
        for i in range(acc_ref.shape[0]):     # per-head static stores
            dq_ref[0, :, i, :] = acc_ref[i].astype(dq_ref.dtype)


def _bwd_dkv_kernel_hb(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, dk_acc, dv_acc, *, sm_scale, causal,
                       offset, bq, bk):
    b, ik, iq = (pl.program_id(i) for i in range(3))
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _compute():
        q = q_ref[0]                                  # (bq, H, D) native
        k = k_ref[0]                                  # (bk, H, D)
        v = v_ref[0]
        do = do_ref[0]                                # (bq, H, D)
        h = q.shape[1]
        lse = lse_ref[0][:, :, None]
        delta = delta_ref[0][:, :, None]
        s, valid = _scores_hb(q, k, sm_scale, causal, iq, ik, bq, bk,
                              offset)
        p = jnp.exp(s - lse)
        if causal and offset < 0:
            p = jnp.where(valid, p, 0.0)
        dpd = _per_head(
            lambda i: _dot2d(do[:, i, :], v[:, i, :], ((1,), (1,))), h)
        ds = p * (dpd - delta)
        # per-head dV_h += P_h^T @ dO_h: (bq, bk) x (bq, D) -> (H, bk, D)
        dv_acc[...] += _per_head(
            lambda i: _dot2d(p[i].astype(do.dtype), do[:, i, :],
                             ((0,), (0,))), h)
        # per-head dK_h += dS_h^T @ Q_h: (bq, bk) x (bq, D) -> (H, bk, D)
        dk_acc[...] += _per_head(
            lambda i: _dot2d(ds[i].astype(q.dtype), q[:, i, :],
                             ((0,), (0,))), h) * sm_scale

    if causal:
        needed = ik * bk <= iq * bq + bq - 1 + offset
        pl.when(needed)(_compute)
    else:
        _compute()

    @pl.when(iq == nq - 1)
    def _finalize():
        for i in range(dk_acc.shape[0]):      # per-head static stores
            dk_ref[0, :, i, :] = dk_acc[i].astype(dk_ref.dtype)
            dv_ref[0, :, i, :] = dv_acc[i].astype(dv_ref.dtype)


def _bwd_impl_hb(q, k, v, out, lse, do, causal, sm_scale, block_q, block_k,
                 interpret):
    bsz, sq, h, d = q.shape
    sk = k.shape[1]
    bq = _pick_block(sq, block_q, interpret)
    bk = _pick_block(sk, block_k, interpret)
    nq, nk = sq // bq, sk // bk
    offset = sk - sq
    # delta = rowsum(dO * O): [B, H, S]
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                            # [B, S, H]
    delta = jnp.transpose(delta, (0, 2, 1))             # [B, H, S] (small)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_hb, sm_scale=sm_scale,
                          causal=causal, offset=offset, bq=bq, bk=bk),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(bsz, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, h, d), lambda b, i, j: (b, i, 0, 0)),
            pl.BlockSpec((1, bk, h, d), lambda b, i, j: (b, j, 0, 0)),
            pl.BlockSpec((1, bk, h, d), lambda b, i, j: (b, j, 0, 0)),
            pl.BlockSpec((1, bq, h, d), lambda b, i, j: (b, i, 0, 0)),
            pl.BlockSpec((1, h, bq), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, h, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, h, d), lambda b, i, j: (b, i, 0, 0)),
        scratch_shapes=[_VMEM((h, bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_hb, sm_scale=sm_scale,
                          causal=causal, offset=offset, bq=bq, bk=bk),
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        grid=(bsz, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, h, d), lambda b, j, i: (b, i, 0, 0)),
            pl.BlockSpec((1, bk, h, d), lambda b, j, i: (b, j, 0, 0)),
            pl.BlockSpec((1, bk, h, d), lambda b, j, i: (b, j, 0, 0)),
            pl.BlockSpec((1, bq, h, d), lambda b, j, i: (b, i, 0, 0)),
            pl.BlockSpec((1, h, bq), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((1, h, bq), lambda b, j, i: (b, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, h, d), lambda b, j, i: (b, j, 0, 0)),
            pl.BlockSpec((1, bk, h, d), lambda b, j, i: (b, j, 0, 0)),
        ],
        scratch_shapes=[_VMEM((h, bk, d), jnp.float32),
                        _VMEM((h, bk, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_hb(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, _ = _fwd_impl_hb(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret)
    return out


def _flash_hb_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _fwd_impl_hb(q, k, v, causal, sm_scale, block_q, block_k,
                            interpret)
    return out, (q, k, v, out, lse)


def _flash_hb_bwd(causal, sm_scale, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    return _bwd_impl_hb(q, k, v, out, lse, do, causal, sm_scale,
                        block_q, block_k, interpret)


_flash_hb.defvjp(_flash_hb_fwd, _flash_hb_bwd)


def flash_attention_bshd_hb(q, k, v, *, causal: bool = False,
                            sm_scale: Optional[float] = None,
                            block_q: int = 512, block_k: int = 512,
                            interpret: Optional[bool] = None):
    """Head-batched flash attention over native ``[B, S, H, D]`` tensors
    (no layout transposes). Requires Hq == Hkv and no dropout — the router
    falls back to :func:`flash_attention_bhsd` otherwise."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    it = _interpret() if interpret is None else interpret
    # re-validate VMEM score budget against the ACTUAL blocks this call
    # will run (supports_hb only checks its default block=512; a direct
    # call with larger blocks must not silently exceed the budget)
    h = q.shape[2]
    bq = _pick_block(q.shape[1], block_q, it)
    bk = _pick_block(k.shape[1], block_k, it)
    if bq is None or bk is None:
        raise ValueError(
            f"flash_attention_bshd_hb: seq lens {q.shape[1]}/{k.shape[1]} "
            f"not tileable by block_q={block_q}/block_k={block_k}")
    if 2 * h * bq * bk * 4 > _VMEM_SCORE_BUDGET:
        raise ValueError(
            f"flash_attention_bshd_hb: scores+probs VMEM "
            f"2*{h}*{bq}*{bk}*4 = {2 * h * bq * bk * 4} bytes exceeds the "
            f"{_VMEM_SCORE_BUDGET} budget; use smaller block_q/block_k or "
            "the per-head kernel (flash_attention_bhsd)")
    return _flash_hb(q, k, v, causal, float(sm_scale), block_q, block_k, it)
