"""Elementwise math + reductions (python/paddle/tensor/math.py parity).

Each op is a differentiable wrapper over jnp — XLA fuses chains of these into
single VPU loops on TPU, playing the role of the reference's elementwise
kernel fusion (phi/kernels/funcs/broadcast_function.h + CINN fusion passes).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.autograd import apply_op
from ..core.tensor import Tensor
from ._helpers import diff_op, nondiff_op, unwrap

__all__ = []


def _export(name, fn):
    globals()[name] = fn
    __all__.append(name)


# ---- unary elementwise -----------------------------------------------------
_UNARY = dict(
    exp=jnp.exp,
    expm1=jnp.expm1,
    log=jnp.log,
    log2=jnp.log2,
    log10=jnp.log10,
    log1p=jnp.log1p,
    sqrt=jnp.sqrt,
    rsqrt=jax.lax.rsqrt,
    square=jnp.square,
    abs=jnp.abs,
    neg=jnp.negative,
    sin=jnp.sin,
    cos=jnp.cos,
    tan=jnp.tan,
    asin=jnp.arcsin,
    acos=jnp.arccos,
    atan=jnp.arctan,
    sinh=jnp.sinh,
    cosh=jnp.cosh,
    tanh=jnp.tanh,
    asinh=jnp.arcsinh,
    acosh=jnp.arccosh,
    atanh=jnp.arctanh,
    ceil=jnp.ceil,
    floor=jnp.floor,
    round=jnp.round,
    trunc=jnp.trunc,
    reciprocal=jnp.reciprocal,
    sign=jnp.sign,
    erf=jax.scipy.special.erf,
    erfinv=jax.scipy.special.erfinv,
    sigmoid=jax.nn.sigmoid,
    digamma=jax.scipy.special.digamma,
    lgamma=jax.scipy.special.gammaln,
    i0=lambda v: jax.scipy.special.i0(v),
    i1=lambda v: jax.scipy.special.i1(v),
    frac=lambda v: v - jnp.trunc(v),
    angle=jnp.angle,
    conj=jnp.conj,
    real=jnp.real,
    imag=jnp.imag,
    deg2rad=jnp.deg2rad,
    rad2deg=jnp.rad2deg,
)
for _n, _f in _UNARY.items():
    _export(_n, diff_op(_f, _n))

# paddle.abs alias
_export("absolute", globals()["abs"])
_export("negative", globals()["neg"])

# ---- binary elementwise ----------------------------------------------------
_BINARY = dict(
    add=jnp.add,
    subtract=jnp.subtract,
    multiply=jnp.multiply,
    divide=jnp.divide,
    floor_divide=jnp.floor_divide,
    mod=jnp.mod,
    remainder=jnp.remainder,
    pow=jnp.power,
    maximum=jnp.maximum,
    minimum=jnp.minimum,
    fmax=jnp.fmax,
    fmin=jnp.fmin,
    atan2=jnp.arctan2,
    hypot=jnp.hypot,
    logaddexp=jnp.logaddexp,
    copysign=jnp.copysign,
    nextafter=jnp.nextafter,
    ldexp=jnp.ldexp,
    heaviside=jnp.heaviside,
    gcd=jnp.gcd,
    lcm=jnp.lcm,
)
for _n, _f in _BINARY.items():
    _export(_n, diff_op(_f, _n))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s, b = unwrap(scale), unwrap(bias)
    if bias_after_scale:
        fn = lambda v: v * s + b
    else:
        fn = lambda v: (v + b) * s
    return apply_op(fn, x, op_name="scale")


_export("scale", scale)


def clip(x, min=None, max=None, name=None):
    mn, mx = unwrap(min), unwrap(max)
    return apply_op(lambda v: jnp.clip(v, mn, mx), x, op_name="clip")


_export("clip", clip)


def lerp(x, y, weight, name=None):
    return apply_op(
        lambda a, b, w: a + w * (b - a), x, y, weight, op_name="lerp"
    )


_export("lerp", lerp)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op(
        lambda v: scale_b * jnp.tanh(scale_a * v), x, op_name="stanh"
    )


_export("stanh", stanh)


def multiplex(inputs, index, name=None):
    vals = [unwrap(i) for i in inputs]
    idx = unwrap(index)
    return apply_op(
        lambda *vs: jnp.stack(vs, 0)[idx.squeeze(-1) if idx.ndim > 1 else idx,
                                     jnp.arange(vs[0].shape[0])],
        *inputs,
        op_name="multiplex",
    )


_export("multiplex", multiplex)

# ---- reductions ------------------------------------------------------------


def _norm_axis(axis):
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis if axis is None else int(axis)


def _reduction(name, fn, int_promote=False):
    def op(x, axis=None, keepdim=False, dtype=None, name=None):
        ax = _norm_axis(axis)
        d = dtypes.convert_dtype(dtype)

        def impl(v):
            out = fn(v, axis=ax, keepdims=keepdim)
            if d is not None:
                out = out.astype(d)
            return out

        return apply_op(impl, x, op_name=name)

    op.__name__ = name
    _export(name, op)
    return op


_reduction("sum", jnp.sum)
_reduction("mean", jnp.mean)
_reduction("prod", jnp.prod)
_reduction("max", jnp.max)
_reduction("min", jnp.min)
_reduction("amax", jnp.max)
_reduction("amin", jnp.min)
_reduction("nansum", jnp.nansum)
_reduction("nanmean", jnp.nanmean)
_reduction("logsumexp", lambda v, axis, keepdims: jax.scipy.special.logsumexp(v, axis=axis, keepdims=keepdims))
_reduction("all", lambda v, axis, keepdims: jnp.all(v, axis=axis, keepdims=keepdims))
_reduction("any", lambda v, axis, keepdims: jnp.any(v, axis=axis, keepdims=keepdims))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return nondiff_op(
        lambda v: jnp.count_nonzero(v, axis=_norm_axis(axis), keepdims=keepdim),
        "count_nonzero",
    )(x)


_export("count_nonzero", count_nonzero)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    ddof = 1 if unbiased else 0
    return apply_op(
        lambda v: jnp.std(v, axis=ax, ddof=ddof, keepdims=keepdim), x, op_name="std"
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    ddof = 1 if unbiased else 0
    return apply_op(
        lambda v: jnp.var(v, axis=ax, ddof=ddof, keepdims=keepdim), x, op_name="var"
    )


def median(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op(
        lambda v: jnp.median(v, axis=ax, keepdims=keepdim), x, op_name="median"
    )


def quantile(x, q, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op(
        lambda v: jnp.quantile(v, jnp.asarray(unwrap(q)), axis=ax, keepdims=keepdim),
        x,
        op_name="quantile",
    )


for _n in ("std", "var", "median", "quantile"):
    _export(_n, globals()[_n])

# ---- cumulative ------------------------------------------------------------


def cumsum(x, axis=None, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype)

    def impl(v):
        if axis is None:
            out = jnp.cumsum(v.reshape(-1))
        else:
            out = jnp.cumsum(v, axis=int(axis))
        return out.astype(d) if d is not None else out

    return apply_op(impl, x, op_name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype)

    def impl(v):
        out = jnp.cumprod(v, axis=int(dim))
        return out.astype(d) if d is not None else out

    return apply_op(impl, x, op_name="cumprod")


def _cum_extreme(x, axis, scan_fn, name):
    """Running max/min values + index of the extremum (last occurrence on ties)."""
    flatten_first = axis is None

    def vals_impl(u):
        if flatten_first:
            return scan_fn(u.reshape(-1), axis=0)
        return scan_fn(u, axis=axis % u.ndim)

    def idx_impl(u):
        if flatten_first:
            u = u.reshape(-1)
            ax = 0
        else:
            ax = axis % u.ndim
        running = scan_fn(u, axis=ax)
        pos_shape = [1] * u.ndim
        pos_shape[ax] = u.shape[ax]
        pos = jnp.arange(u.shape[ax]).reshape(pos_shape)
        pos = jnp.broadcast_to(pos, u.shape)
        candidate = jnp.where(u == running, pos, -1)
        return jax.lax.cummax(candidate, axis=ax).astype(dtypes.int64)

    vals = apply_op(vals_impl, x, op_name=name)
    idx = nondiff_op(idx_impl, name + "_idx")(x)
    return vals, idx


def cummax(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, jax.lax.cummax, "cummax")


def cummin(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, jax.lax.cummin, "cummin")


def logcumsumexp(x, axis=None, name=None):
    def impl(v):
        if axis is None:
            return jax.lax.cumlogsumexp(v.reshape(-1))
        return jax.lax.cumlogsumexp(v, axis=int(axis))

    return apply_op(impl, x, op_name="logcumsumexp")


for _n in ("cumsum", "cumprod", "cummax", "cummin", "logcumsumexp"):
    _export(_n, globals()[_n])

# ---- misc ------------------------------------------------------------------


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op(
        lambda i, a, b: beta * i + alpha * (a @ b), input, x, y, op_name="addmm"
    )


def inner(x, y, name=None):
    return apply_op(jnp.inner, x, y, op_name="inner")


def outer(x, y, name=None):
    return apply_op(
        lambda a, b: jnp.outer(a.reshape(-1), b.reshape(-1)), x, y, op_name="outer"
    )


def kron(x, y, name=None):
    return apply_op(jnp.kron, x, y, op_name="kron")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(
        lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2),
        x,
        op_name="trace",
    )


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre, app = unwrap(prepend), unwrap(append)
    return apply_op(
        lambda v: jnp.diff(v, n=n, axis=axis, prepend=pre, append=app),
        x,
        op_name="diff",
    )


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op(
        lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf),
        x,
        op_name="nan_to_num",
    )


def increment(x, value=1.0, name=None):
    x._inplace_(x._value + value)
    return x


def floor_mod(x, y, name=None):
    return apply_op(jnp.mod, x, y, op_name="floor_mod")


def divide_no_nan(x, y, name=None):
    return apply_op(
        lambda a, b: jnp.where(b == 0, jnp.zeros_like(a), a / jnp.where(b == 0, 1, b)),
        x,
        y,
        op_name="divide_no_nan",
    )


for _n in (
    "addmm",
    "inner",
    "outer",
    "kron",
    "trace",
    "diff",
    "nan_to_num",
    "increment",
    "floor_mod",
    "divide_no_nan",
):
    _export(_n, globals()[_n])


# ---- round-2 long tail (reference python/paddle/tensor/math.py) ------------


def logit(x, eps=None, name=None):
    """log(p/(1-p)); eps clamps inputs into [eps, 1-eps] (math.py logit)."""
    def f(v):
        p = jnp.clip(v, eps, 1.0 - eps) if eps is not None else v
        return jnp.log(p) - jnp.log1p(-p)

    return apply_op(f, x, op_name="logit")


def frexp(x, name=None):
    """Mantissa/exponent decomposition (math.py frexp): x = m * 2**e with
    0.5 <= |m| < 1."""
    from ._helpers import nondiff_op as _nd

    def f(v):
        e = jnp.where(v == 0, 0, jnp.floor(jnp.log2(jnp.abs(
            jnp.where(v == 0, 1.0, v)))) + 1)
        m = v / jnp.exp2(e)
        # float log2 can round up at power-of-two boundaries, leaving
        # |m| < 0.5 — renormalize so the 0.5 <= |m| < 1 contract holds
        fix = (jnp.abs(m) < 0.5) & (v != 0)
        m = jnp.where(fix, m * 2, m)
        e = jnp.where(fix, e - 1, e)
        return m, e.astype(v.dtype)

    return _nd(f, "frexp")(x)


def i0e(x, name=None):
    return apply_op(lambda v: jax.scipy.special.i0e(v), x, op_name="i0e")


def i1e(x, name=None):
    return apply_op(lambda v: jax.scipy.special.i1e(v), x, op_name="i1e")


def polygamma(x, n, name=None):
    return apply_op(lambda v: jax.scipy.special.polygamma(n, v), x,
                    op_name="polygamma")


def sgn(x, name=None):
    """sign for real; x/|x| for complex (math.py sgn)."""
    def f(v):
        if jnp.iscomplexobj(v):
            m = jnp.abs(v)
            return jnp.where(m == 0, 0, v / jnp.where(m == 0, 1, m))
        return jnp.sign(v)

    return apply_op(f, x, op_name="sgn")


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Trapezoidal integration (math.py trapezoid)."""
    if x is not None:
        return apply_op(lambda yv, xv: jnp.trapezoid(yv, xv, axis=axis),
                        y, x, op_name="trapezoid")
    return apply_op(
        lambda yv: jnp.trapezoid(yv, dx=(dx if dx is not None else 1.0),
                                 axis=axis), y, op_name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Cumulative trapezoid (math.py cumulative_trapezoid)."""
    def f(yv, xv=None):
        y1 = jnp.moveaxis(yv, axis, -1)
        avg = (y1[..., 1:] + y1[..., :-1]) * 0.5
        if xv is not None:
            x1 = jnp.moveaxis(jnp.broadcast_to(xv, yv.shape), axis, -1) \
                if xv.ndim > 1 else xv
            d = jnp.diff(x1, axis=-1)
        else:
            d = dx if dx is not None else 1.0
        return jnp.moveaxis(jnp.cumsum(avg * d, axis=-1), -1, axis)

    if x is not None:
        return apply_op(f, y, x, op_name="cumulative_trapezoid")
    return apply_op(f, y, op_name="cumulative_trapezoid")


def renorm(x, p, axis, max_norm, name=None):
    """Renormalize slices along `axis` to at most max_norm in p-norm
    (math.py renorm)."""
    def f(v):
        moved = jnp.moveaxis(v, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
        factor = jnp.where(norms > max_norm,
                           max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * factor[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)

    return apply_op(f, x, op_name="renorm")


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda v: jnp.nanmedian(v, axis=axis, keepdims=keepdim), x,
        op_name="nanmedian")


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda v: jnp.nanquantile(v, q, axis=axis, keepdims=keepdim).astype(
            jnp.float32 if v.dtype != jnp.float64 else v.dtype),
        x, op_name="nanquantile")


def vander(x, n=None, increasing=False, name=None):
    return apply_op(
        lambda v: jnp.vander(v, N=n, increasing=increasing), x,
        op_name="vander")


def add_n(inputs, name=None):
    """Sum a list of tensors (math.py add_n / legacy sum op)."""
    if isinstance(inputs, (list, tuple)):
        import functools as _ft

        # NB: builtin sum is shadowed by this module's reduction op
        return apply_op(lambda *vs: _ft.reduce(jnp.add, vs), *inputs,
                        op_name="add_n")
    return apply_op(lambda v: v, inputs, op_name="add_n")


for _n in ("logit", "frexp", "i0e", "i1e", "polygamma", "sgn", "trapezoid",
           "cumulative_trapezoid", "renorm", "nanmedian", "nanquantile",
           "vander", "add_n"):
    _export(_n, globals()[_n])
