"""Explicit-residual flash attention: fwd returns (out, lse), bwd consumes
them — no closure, so the pair can straddle a pipeline schedule.

``jax.vjp``'s backward closure cannot ride a ``lax.scan`` carry; the 1F1B
residual-stashing schedule (pp_sharded.build_sharded_1f1b_resid_grad_fn)
needs attention backward as a PURE function of stashable arrays. This module
exposes exactly that pair in the paddle ``[B, S, H, D]`` layout:

- ``flash_fwd_res(q, k, v, causal)   -> (out, lse)``
- ``flash_bwd_res(q, k, v, out, lse, do, causal) -> (dq, dk, dv)``

TPU routes to this framework's Pallas kernels (flash_attention_kernel.py
``_fwd_impl``/``_bwd_impl`` — the same code the custom_vjp path runs, so
numerics are identical); other backends use a jnp composition that
materializes the [B, H, Sq, Sk] score matrix (test-scale only — the TPU
path never does).

Reference analog: phi/kernels/gpu/flash_attn_grad_kernel.cu consumes the
softmax_lse the forward kernel saved (flash_attn_kernel.cu:213) — the same
explicit-residual contract.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["flash_fwd_res", "flash_bwd_res"]


def _use_kernel(q, k, interpret) -> bool:
    from .flash_attention_kernel import _interpret, supports

    it = _interpret() if interpret is None else interpret
    on_tpu = False
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        pass
    return (on_tpu or it) and supports(q.shape[1], k.shape[1], it)


def _blocks(q, k, causal):
    from .autotune import flash_signature, lookup

    tuned = lookup("flash_attention",
                   flash_signature(q.shape[1], k.shape[1], q.shape[-1],
                                   causal, jnp.dtype(q.dtype).name)) or {}
    return tuned.get("block_q", 1024), tuned.get("block_k", 1024)


def _mask(sq, sk, causal):
    if not causal:
        return None
    # bottom-right alignment: query i attends keys <= i + (sk - sq)
    qpos = jnp.arange(sq)[:, None]
    kpos = jnp.arange(sk)[None, :]
    return qpos + (sk - sq) >= kpos


def _scores(q, k, sm_scale):
    # q,k: [B,S,H,D] -> [B,H,Sq,Sk] fp32
    return jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                      k.astype(jnp.float32)) * sm_scale


def _rep_kv(q, k, v):
    g = q.shape[2] // k.shape[2]
    if g > 1:
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
    return k, v, g


def flash_fwd_res(q, k, v, *, causal: bool = False,
                  sm_scale: Optional[float] = None,
                  interpret: Optional[bool] = None
                  ) -> Tuple[jax.Array, jax.Array]:
    """[B, S, H, D] in; returns (out [B,S,H,D], lse [B,H,S] fp32)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if _use_kernel(q, k, interpret):
        from .flash_attention_kernel import _fwd_impl, _interpret

        it = _interpret() if interpret is None else interpret
        bq, bk = _blocks(q, k, causal)
        qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
        seed = jnp.zeros((1,), jnp.int32)
        out, lse = _fwd_impl(qt, kt, vt, seed, causal, float(sm_scale),
                             0.0, bq, bk, it)
        return jnp.swapaxes(out, 1, 2), lse
    kr, vr, _ = _rep_kv(q, k, v)
    s = _scores(q, kr, sm_scale)
    m = _mask(q.shape[1], k.shape[1], causal)
    if m is not None:
        s = jnp.where(m[None, None], s, -jnp.inf)
    lse = jax.nn.logsumexp(s, axis=-1)                      # [B,H,Sq]
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), vr)
    return out.astype(q.dtype), lse


def flash_bwd_res(q, k, v, out, lse, do, *, causal: bool = False,
                  sm_scale: Optional[float] = None,
                  interpret: Optional[bool] = None):
    """Gradient of flash attention from stashed (q, k, v, out, lse).
    Linear in ``do`` (a zero cotangent yields zero grads — the pipeline
    schedule relies on this to mask invalid ticks)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if _use_kernel(q, k, interpret):
        from .flash_attention_kernel import _bwd_impl, _interpret

        it = _interpret() if interpret is None else interpret
        bq, bk = _blocks(q, k, causal)
        qt, kt, vt, ot, dot = (jnp.swapaxes(x, 1, 2)
                               for x in (q, k, v, out, do))
        seed = jnp.zeros((1,), jnp.int32)
        dq, dk, dv = _bwd_impl(qt, kt, vt, seed, ot, lse, dot, causal,
                               float(sm_scale), 0.0, bq, bk, it)
        return (jnp.swapaxes(dq, 1, 2), jnp.swapaxes(dk, 1, 2),
                jnp.swapaxes(dv, 1, 2))
    kr, vr, g = _rep_kv(q, k, v)
    s = _scores(q, kr, sm_scale)
    m = _mask(q.shape[1], k.shape[1], causal)
    if m is not None:
        s = jnp.where(m[None, None], s, -jnp.inf)
    p = jnp.exp(s - lse[..., None])                         # [B,H,Sq,Sk]
    dof = do.astype(jnp.float32)
    dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vr.astype(jnp.float32))
    delta = jnp.einsum("bqhd,bqhd->bhq", dof, out.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * sm_scale
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, kr.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, q.astype(jnp.float32))
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
    if g > 1:
        b, sk_, hq, d = dk.shape
        dk = dk.reshape(b, sk_, hq // g, g, d).sum(axis=3)
        dv = dv.reshape(b, sk_, hq // g, g, d).sum(axis=3)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)
