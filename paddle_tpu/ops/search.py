"""Search / sort ops (python/paddle/tensor/search.py parity)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from ._helpers import nondiff_op, unwrap
from ..core.dtype import int64 as _i64

__all__ = [
    "argmax",
    "argmin",
    "argsort",
    "sort",
    "topk",
    "nonzero",
    "masked_select",
    "searchsorted",
    "kthvalue",
    "mode",
    "unique",
    "unique_consecutive",
]


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def impl(v):
        out = jnp.argmax(v if axis is not None else v.reshape(-1),
                         axis=axis if axis is not None else 0)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        from ..core.dtype import convert_dtype
        return out.astype(convert_dtype(dtype))

    return nondiff_op(impl, "argmax")(x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def impl(v):
        out = jnp.argmin(v if axis is not None else v.reshape(-1),
                         axis=axis if axis is not None else 0)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        from ..core.dtype import convert_dtype
        return out.astype(convert_dtype(dtype))

    return nondiff_op(impl, "argmin")(x)


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def impl(v):
        idx = jnp.argsort(v, axis=axis, stable=True, descending=descending)
        return idx.astype(_i64)

    return nondiff_op(impl, "argsort")(x)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def impl(v):
        out = jnp.sort(v, axis=axis, stable=True, descending=descending)
        return out

    return apply_op(impl, x, op_name="sort")


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    k = int(unwrap(k))
    ax = int(axis)

    def vals_impl(v):
        u = jnp.moveaxis(v, ax, -1)
        if largest:
            tv, _ = jax.lax.top_k(u, k)
        else:
            tv, _ = jax.lax.top_k(-u, k)
            tv = -tv
        return jnp.moveaxis(tv, -1, ax)

    def idx_impl(v):
        u = jnp.moveaxis(v, ax, -1)
        _, ti = jax.lax.top_k(u if largest else -u, k)
        return jnp.moveaxis(ti.astype(_i64), -1, ax)

    values = apply_op(vals_impl, x, op_name="topk")
    indices = nondiff_op(idx_impl, "topk_idx")(x)
    return values, indices


def nonzero(x, as_tuple=False, name=None):
    v = unwrap(x)
    idx = jnp.nonzero(v)  # host-sync: dynamic shape, eager-only
    if as_tuple:
        return tuple(Tensor(i.reshape(-1, 1).squeeze(-1)) for i in idx)
    return Tensor(jnp.stack(idx, axis=-1).astype(_i64))


def masked_select(x, mask, name=None):
    # dynamic shape: eager-only (reference: masked_select op). Taped: the
    # grad scatters the cotangent back into the mask positions.
    from ._helpers import diff_op

    return diff_op(lambda v, m: v[m], "masked_select")(x, mask)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def impl(s, v):
        out = jnp.searchsorted(s, v, side="right" if right else "left")
        return out.astype(jnp.int32 if out_int32 else _i64)

    return nondiff_op(impl, "searchsorted")(sorted_sequence, values)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    ax = int(axis)

    def vals(v):
        s = jnp.sort(v, axis=ax)
        out = jnp.take(s, k - 1, axis=ax)
        return jnp.expand_dims(out, ax) if keepdim else out

    def idxs(v):
        si = jnp.argsort(v, axis=ax)
        out = jnp.take(si, k - 1, axis=ax).astype(_i64)
        return jnp.expand_dims(out, ax) if keepdim else out

    return apply_op(vals, x, op_name="kthvalue"), nondiff_op(idxs, "kthvalue_idx")(x)


def mode(x, axis=-1, keepdim=False, name=None):
    v = unwrap(x)
    ax = int(axis)

    def _mode_1d(row):
        vals, counts = jnp.unique_counts(row, size=row.shape[0], fill_value=row[0])
        i = jnp.argmax(counts)
        return vals[i]

    u = jnp.moveaxis(v, ax, -1)
    flat = u.reshape(-1, u.shape[-1])
    out = jax.vmap(_mode_1d)(flat).reshape(u.shape[:-1])
    idx = jnp.argmax(
        jnp.moveaxis(v, ax, -1) == out[..., None], axis=-1
    ).astype(_i64)
    if keepdim:
        out = jnp.expand_dims(out, ax)
        idx = jnp.expand_dims(idx, ax)
    return Tensor(out), Tensor(idx)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    v = unwrap(x)
    res = jnp.unique(
        v, return_index=return_index, return_inverse=return_inverse,
        return_counts=return_counts, axis=axis,
    )  # dynamic shape: eager-only
    if not (return_index or return_inverse or return_counts):
        return Tensor(res)
    return tuple(Tensor(r) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    import numpy as np

    v = np.asarray(unwrap(x))
    if axis is None:
        v = v.reshape(-1)
        keep = np.concatenate([[True], v[1:] != v[:-1]])
    else:
        diff = (v.take(range(1, v.shape[axis]), axis=axis)
                != v.take(range(0, v.shape[axis] - 1), axis=axis))
        keep = np.concatenate(
            [[True], diff.reshape(diff.shape[axis] if v.ndim == 1 else -1, *[])
             .any(axis=tuple(i for i in range(diff.ndim) if i != axis))]
        ) if v.ndim > 1 else np.concatenate([[True], diff])
    out = v.compress(keep, axis=axis if axis is not None else 0)
    outs = [Tensor(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, len(keep)))
        outs.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    """Bucket indices into a 1-D sorted sequence (search.py bucketize)."""
    import jax.numpy as jnp

    from ._helpers import nondiff_op

    def f(v, seq):
        side = "right" if right else "left"
        out = jnp.searchsorted(seq, v, side=side)
        return out.astype(jnp.int32) if out_int32 else out

    return nondiff_op(f, "bucketize")(x, sorted_sequence)


__all__.append("bucketize")
