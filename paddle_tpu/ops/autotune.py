"""Kernel autotune cache (reference analog: paddle/phi/kernels/autotune/
— cache.h AlgorithmsCache + auto_tune_base.h tuner that times candidate
kernels and caches the winner per input signature).

TPU-native shape: Pallas kernels are compiled per block config, so the
tunable is the BLOCK SIZE tuple, not a cuDNN algo id. Because kernels are
normally called inside ``jit`` traces (where timing is impossible), tuning
runs eagerly and out-of-band — ``tune(...)`` benchmarks candidates on the
real device once, and the winning config is consulted at trace time from a
process-wide (optionally persisted) cache.

    from paddle_tpu.ops import autotune
    autotune.tune("flash_attention", (8, 8, 2048, 128), candidates=...,
                  runner=...)         # or autotune.tune_flash(...)
    # subsequent flash_attention calls pick up the tuned blocks

``FLAGS_use_autotune`` (framework.flags) gates lookup; the cache file
defaults to ``~/.paddle_tpu_autotune.json``.
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, Iterable, Optional, Sequence, Tuple

__all__ = ["AutoTuneCache", "get_cache", "lookup", "record", "tune",
           "tune_flash", "tune_decode_mha", "decode_signature",
           "set_cache_path"]

_CACHE_ENV = "PADDLE_TPU_AUTOTUNE_CACHE"


def _repo_cache_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), ".autotune_cache.json")


def _default_path() -> str:
    """WRITE path: env override or the per-user file — never the
    committed in-repo cache (a local tune() on non-TPU hardware must not
    dirty/poison the version-controlled real-hardware results; the sweep
    script opts into the repo path via set_cache_path)."""
    return os.environ.get(
        _CACHE_ENV, os.path.join(os.path.expanduser("~"),
                                 ".paddle_tpu_autotune.json"))


class AutoTuneCache:
    """(op, signature) -> winning config dict, with hit/miss counters
    (reference cache.h keeps the same stats)."""

    def __init__(self, path: Optional[str] = None):
        self._table: Dict[str, dict] = {}
        self._hits = 0
        self._misses = 0
        self._path = path

    @staticmethod
    def _key(op: str, signature: Sequence) -> str:
        return f"{op}:{','.join(str(s) for s in signature)}"

    def lookup(self, op: str, signature: Sequence) -> Optional[dict]:
        rec = self._table.get(self._key(op, signature))
        if rec is None:
            self._misses += 1
            return None
        self._hits += 1
        return rec

    def record(self, op: str, signature: Sequence, config: dict):
        self._table[self._key(op, signature)] = dict(config)

    @property
    def stats(self):
        return {"hits": self._hits, "misses": self._misses,
                "size": len(self._table)}

    # -- persistence -------------------------------------------------------
    def save(self, path: Optional[str] = None):
        """Atomic write (temp + rename): a sweep trial can be group-killed
        mid-save, and a truncated committed cache would poison every later
        trial's merge-load."""
        path = path or self._path or _default_path()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self._table, f, indent=1, sort_keys=True)
        os.replace(tmp, path)

    def load(self, path: Optional[str] = None) -> bool:
        path = path or self._path or _default_path()
        if not os.path.exists(path):
            return False
        with open(path) as f:
            self._table.update(json.load(f))
        return True


_GLOBAL = AutoTuneCache()
_loaded = [False]


def get_cache() -> AutoTuneCache:
    if not _loaded[0]:
        _loaded[0] = True
        # READ order: per-user file first, then the committed in-repo
        # cache (real-hardware sweep results) so the repo entries win
        for path in (_default_path(), _repo_cache_path()):
            try:
                _GLOBAL.load(path)
            except (OSError, ValueError):
                pass
    return _GLOBAL


def set_cache_path(path: str):
    _GLOBAL._path = path


def _enabled() -> bool:
    from ..framework.flags import get_flags

    return bool(get_flags("FLAGS_use_autotune").get("FLAGS_use_autotune",
                                                    True))


def lookup(op: str, signature: Sequence) -> Optional[dict]:
    if not _enabled():
        return None
    return get_cache().lookup(op, signature)


def record(op: str, signature: Sequence, config: dict):
    get_cache().record(op, signature, config)


def tune(op: str, signature: Sequence, candidates: Iterable[dict],
         runner: Callable[[dict], None], warmup: int = 1, iters: int = 3,
         save: bool = True) -> dict:
    """Time ``runner(config)`` for every candidate, record the winner.

    ``runner`` must execute the kernel to completion (block on a host
    readback — through a remote-dispatch tunnel ``block_until_ready`` can
    return before the device finishes).
    """
    best_cfg, best_t = None, float("inf")
    results = []
    for cfg in candidates:
        try:
            for _ in range(warmup):
                runner(cfg)
            t0 = time.perf_counter()
            for _ in range(iters):
                runner(cfg)
            dt = (time.perf_counter() - t0) / iters
        except Exception as e:  # candidate doesn't compile/fit — skip
            results.append({**cfg, "error": str(e)[:120]})
            continue
        results.append({**cfg, "ms": dt * 1e3})
        if dt < best_t:
            best_cfg, best_t = dict(cfg), dt
    if best_cfg is None:
        raise RuntimeError(f"autotune: no candidate for {op} worked: "
                           f"{results}")
    best_cfg["ms"] = best_t * 1e3
    record(op, signature, best_cfg)
    if save:
        try:
            get_cache().save()
        except OSError:
            pass
    return best_cfg


# -- flash attention ------------------------------------------------------

FLASH_BLOCK_CANDIDATES = ((1024, 1024), (512, 1024), (1024, 512),
                          (512, 512), (256, 1024), (512, 2048))


def flash_signature(sq: int, sk: int, d: int, causal: bool,
                    dtype="bfloat16") -> Tuple:
    # dtype is part of the key: a block config tuned for bf16 has half the
    # VMEM footprint of the same config at fp32
    return ("sq", sq, "sk", sk, "d", d, "causal", int(causal),
            "dtype", str(dtype))


def tune_flash(b: int, h: int, s: int, d: int, causal: bool = True,
               dtype="bfloat16", candidates=FLASH_BLOCK_CANDIDATES,
               grad: bool = True) -> dict:
    """Benchmark flash block sizes at [b, h, s, d] and cache the winner
    (keyed by sequence/head-dim — batch/head count only scale the grid)."""
    import jax
    import jax.numpy as jnp

    from .flash_attention_kernel import flash_attention_bhsd

    key = jax.random.PRNGKey(0)
    dt = jnp.dtype(dtype)
    q = jax.random.normal(key, (b, h, s, d), dt)
    k = jax.random.normal(key, (b, h, s, d), dt)
    v = jax.random.normal(key, (b, h, s, d), dt)

    def runner(cfg):
        bq, bk = cfg["block_q"], cfg["block_k"]
        if grad:
            def f(q, k, v):
                return jnp.sum(flash_attention_bhsd(
                    q, k, v, causal=causal, block_q=bq,
                    block_k=bk).astype(jnp.float32))
            out = jax.grad(f)(q, k, v)
            float(jnp.sum(out))  # host readback barrier
        else:
            out = flash_attention_bhsd(q, k, v, causal=causal,
                                       block_q=bq, block_k=bk)
            float(jnp.sum(out.astype(jnp.float32)))

    cands = [{"block_q": bq, "block_k": bk} for bq, bk in candidates
             if bq <= s and bk <= s]
    return tune("flash_attention", flash_signature(s, s, d, causal, dtype),
                cands, runner)


# -- decode attention -----------------------------------------------------

DECODE_BLOCK_CANDIDATES = (256, 512, 1024, 2048)


def decode_signature(s_max: int, h: int, d: int, dtype="bfloat16") -> Tuple:
    return ("s_max", s_max, "h", h, "d", d, "dtype", str(dtype))


def tune_decode_mha(b: int, h: int, s_max: int, d: int, dtype="bfloat16",
                    candidates=DECODE_BLOCK_CANDIDATES) -> dict:
    """Benchmark decode_mha S-block sizes at [b, h, s_max, d] over a
    mixed-length batch (the serving shape) and cache the winner."""
    import jax
    import jax.numpy as jnp

    from .pallas_kernels import decode_mha

    key = jax.random.PRNGKey(0)
    dt = jnp.dtype(dtype)
    q = jax.random.normal(key, (b, h, d), dt)
    kc = jax.random.normal(key, (b, s_max, h, d), dt)
    vc = jax.random.normal(key, (b, s_max, h, d), dt)
    lens = jnp.linspace(s_max // 8, s_max, b).astype(jnp.int32)

    def runner(cfg):
        out = decode_mha(q, kc, vc, lens, block_s=cfg["block_s"])
        float(jnp.sum(out.astype(jnp.float32)))   # host readback barrier

    cands = [{"block_s": bs} for bs in candidates if bs <= s_max]
    return tune("decode_mha", decode_signature(s_max, h, d, dtype),
                cands, runner)
