"""Decode-step attention dispatch (single query token over a KV cache).

Reference analog: masked_multihead_attention_kernel
(fused_multi_transformer_op.cu.h:745). MHA routes to the tiled Pallas
decode kernel on TPU; GQA uses a grouped einsum composition — the decode
step is HBM-bandwidth-bound (the whole cache streams once either way), so
XLA's fused gather+softmax is within noise of a hand kernel for grouped
heads while keeping the KV cache un-repeated.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = ["gqa_decode_attention"]


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def gqa_decode_attention(q, k_cache, v_cache, seq_lens, tp=None):
    """q: [B, Hq, D]; k/v_cache: [B, S, Hkv, D]; seq_lens: [B] valid rows
    (the current token's K/V already written at seq_lens-1).
    Returns [B, Hq, D] in q's dtype.

    ``tp=(mesh, axis)`` wraps the step in ``shard_map`` over the head
    axis (q on Hq, caches on Hkv, lens replicated): attention is
    head-parallel, so each mesh shard runs this exact function on its
    local slice with zero communication — the tensor-parallel serving
    engines' dense-cache decode path (see ``inference/tp.py``)."""
    if tp is not None:
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        mesh, ax = tp
        head, kv = P(None, ax, None), P(None, None, ax, None)
        return shard_map(
            lambda q_, k_, v_, l_: gqa_decode_attention(q_, k_, v_, l_),
            mesh=mesh, in_specs=(head, kv, kv, P()), out_specs=head,
            check_rep=False)(q, k_cache, v_cache, seq_lens)
    b, hq, d = q.shape
    s_max, hkv = k_cache.shape[1], k_cache.shape[2]
    if hq == hkv and _on_tpu():
        from .pallas_kernels import decode_mha

        return decode_mha(q, k_cache, v_cache, seq_lens)
    g = hq // hkv
    scale = 1.0 / math.sqrt(d)
    q4 = q.reshape(b, hkv, g, d).astype(jnp.float32)
    kc = k_cache.astype(jnp.float32)
    vc = v_cache.astype(jnp.float32)
    s = jnp.einsum("bkgd,bskd->bkgs", q4, kc) * scale     # [B, Hkv, G, S]
    mask = jnp.arange(s_max)[None, None, None, :] < seq_lens[:, None, None,
                                                             None]
    s = jnp.where(mask, s, -1e30)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - jnp.where(jnp.isfinite(m), m, 0.0))
    p = jnp.where(mask, p, 0.0)
    p = p / jnp.maximum(jnp.sum(p, -1, keepdims=True), 1e-30)
    o = jnp.einsum("bkgs,bskd->bkgd", p, vc)
    return o.reshape(b, hq, d).astype(q.dtype)
