"""Pallas TPU kernel surface.

The fused-op set the reference implements as hand-written CUDA
(fluid/operators/fused/fused_multi_transformer_op.cu, phi/kernels/gpu/
flash_attn_kernel.cu, fused_rope_kernel.cu, ...) maps here to Pallas TPU
kernels. Flash/paged attention and MoE grouped-matmul use the Pallas kernels
shipped with JAX (jax.experimental.pallas.ops.tpu — maintained, MXU-tuned);
the remaining fused set (rope, bias-dropout-residual-LN, KV-cache decode
step) are hand-written in paddle_tpu/ops/pallas_kernels/.

Non-TPU backends fall back to a chunked XLA composition (no S² HBM
materialisation) so tests run anywhere.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = ["flash_attention", "paged_attention", "grouped_matmul",
           "prefix_chunk_attention"]


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _chunked_attention(q, k, v, causal: bool, sm_scale: float,
                       chunk: int = 512, q_offset=None):
    """Memory-efficient attention fallback: online-softmax over key chunks
    (the flash-attention recurrence expressed in XLA; no [S,S] buffer).

    ``q_offset`` (a traced int32, or None) switches the causal mask to
    ABSOLUTE positions: query row i sits at position ``q_offset + i`` and
    attends keys at ``kpos <= q_offset + i`` — the chunked-prefill form,
    where q is one fixed-shape chunk of a prompt and k/v are the whole
    (partially written) KV cache."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    nchunk = max(1, (sk + chunk - 1) // chunk)
    csize = (sk + nchunk - 1) // nchunk
    # pad keys to multiple
    pad = nchunk * csize - sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kc = k.reshape(b, h, nchunk, csize, d)
    vc = v.reshape(b, h, nchunk, csize, d)
    qpos = jnp.arange(sq)

    def body(carry, idx):
        acc, m, l = carry
        kk = kc[:, :, idx]
        vv = vc[:, :, idx]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kk) * sm_scale
        s = s.astype(jnp.float32)
        kpos = idx * csize + jnp.arange(csize)
        valid = kpos < sk
        if q_offset is not None:
            # absolute-position causal: the chunked-prefill mask
            valid = valid[None, :] & (
                q_offset + qpos[:, None] >= kpos[None, :])
        elif causal:
            # bottom-right alignment (queries end at the last key): the
            # decode-with-KV-cache convention, matching _sdpa_ref's
            # tril(k=sk-sq) — query i attends keys <= i + (sk - sq)
            valid = valid[None, :] & (
                qpos[:, None] + (sk - sq) >= kpos[None, :])
        else:
            valid = jnp.broadcast_to(valid[None, :], (sq, csize))
        s = jnp.where(valid[None, None], s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # guard all -inf rows
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(valid[None, None], p, 0.0)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
        alpha = jnp.where(jnp.isfinite(alpha), alpha, 0.0)
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(v.dtype), vv).astype(jnp.float32)
        return (acc, m_new, l), None

    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(body, (acc0, m0, l0), jnp.arange(nchunk))
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def flash_attention(q, k, v, causal: bool = False, sm_scale: float = None,
                    dropout_p: float = 0.0, seed=None, tp=None):
    """[B, S, H, D] paddle layout; GQA allowed (K/V may carry fewer heads).

    ``tp=(mesh, axis)`` shard_maps the whole call over the head axis
    (q on H, k/v on their own Hkv) — the tensor-parallel serving
    engines' prefill path: each mesh shard runs the unmodified
    kernel/fallback on its local head slice, zero attention-side
    communication (see ``inference/tp.py``).

    TPU: this framework's own Pallas flash kernel
    (ops/flash_attention_kernel.py — reference analog:
    phi/kernels/gpu/flash_attn_kernel.cu:213) with bottom-right causal
    alignment, grouped KV in the index maps, and in-kernel dropout.
    Unsupported shapes / non-TPU: chunked online-softmax XLA fallback
    (dropout not available there — callers route dropout elsewhere).
    """
    if tp is not None:
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        mesh, ax = tp
        hs = P(None, None, ax, None)
        return shard_map(
            lambda q_, k_, v_: flash_attention(
                q_, k_, v_, causal=causal, sm_scale=sm_scale,
                dropout_p=dropout_p, seed=seed),
            mesh=mesh, in_specs=(hs, hs, hs), out_specs=hs,
            check_rep=False)(q, k, v)
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)

    # head-batched BSHD-native path: no layout transposes (PERF.md ~11ms/
    # step at bench shapes). Opt-in until TPU-measured faster — flip
    # FLAGS_flash_head_batched once experiments/exp_flash_hb.py says so.
    from ..framework.flags import get_flags

    if get_flags("FLAGS_flash_head_batched")["FLAGS_flash_head_batched"] \
            and _on_tpu():
        from .flash_attention_hb import (flash_attention_bshd_hb,
                                         supports_hb)

        if supports_hb(q.shape, k.shape, dropout_p):
            return flash_attention_bshd_hb(q, k, v, causal=causal,
                                           sm_scale=scale)

    qt = jnp.swapaxes(q, 1, 2)  # [B, H, S, D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    from .flash_attention_kernel import flash_attention_bhsd, supports

    # off-TPU the kernel runs in interpret mode (~17x slower than the XLA
    # fallback) — only worth it when in-kernel dropout semantics are needed
    use_kernel = supports(qt.shape[2], kt.shape[2]) and (
        _on_tpu() or dropout_p > 0.0)
    if use_kernel:
        out = flash_attention_bhsd(qt, kt, vt, causal=causal, sm_scale=scale,
                                   dropout_p=dropout_p, seed=seed)
    else:
        if dropout_p > 0.0:
            raise ValueError("dropout requires the Pallas kernel path "
                             "(seq lens must be block-divisible)")
        if kt.shape[1] != qt.shape[1]:  # GQA fallback: materialize groups
            rep = qt.shape[1] // kt.shape[1]
            kt = jnp.repeat(kt, rep, axis=1)
            vt = jnp.repeat(vt, rep, axis=1)
        out = _chunked_attention(qt, kt, vt, causal, scale)
    return jnp.swapaxes(out, 1, 2)


def prefix_chunk_attention(q, k_cache, v_cache, pos, sm_scale: float = None,
                           tp=None):
    """Chunked/padded-prefill attention: queries at ABSOLUTE positions
    ``[pos, pos+S)`` attend causally over the written prefix of a KV
    cache (the chunk's own K/V already written at ``[pos, pos+S)``).

    ``tp=(mesh, axis)`` shard_maps the recurrence over the head axis
    (``pos`` replicates) — the tensor-parallel chunked-prefill /
    warm-admission / spec-verify path (see ``inference/tp.py``).

    q: [B, S, H, D]; k/v_cache: [B, W, Hkv, D] (GQA allowed); pos: traced
    int32 scalar. Returns [B, S, H, D] in q's dtype.

    This is the SAME online-softmax recurrence as the one-shot
    ``flash_attention`` fallback — masked-out cache columns contribute
    exact float zeros to every reduction — so at cache widths within one
    key chunk (<= 512) a prompt prefilled in fixed-shape chunks at traced
    offsets, or padded up to a length bucket, reproduces single-shot
    prefill logits and KV BITWISE (beyond one chunk the key-chunk
    boundaries differ between widths and identity degrades to ~1-ulp).
    The serving engines' bounded-compile prefill rides on this: one
    compiled program per (chunk shape, cache width), reused at every
    offset, instead of one per distinct prompt length.
    """
    if tp is not None:
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        mesh, ax = tp
        hs = P(None, None, ax, None)
        return shard_map(
            lambda q_, k_, v_, p_: prefix_chunk_attention(
                q_, k_, v_, p_, sm_scale=sm_scale),
            mesh=mesh, in_specs=(hs, hs, hs, P()), out_specs=hs,
            check_rep=False)(q, k_cache, v_cache, pos)
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    qt = jnp.swapaxes(q, 1, 2)          # [B, H, S, D]
    kt = jnp.swapaxes(k_cache, 1, 2)
    vt = jnp.swapaxes(v_cache, 1, 2)
    if kt.shape[1] != qt.shape[1]:      # GQA fallback: materialize groups
        rep = qt.shape[1] // kt.shape[1]
        kt = jnp.repeat(kt, rep, axis=1)
        vt = jnp.repeat(vt, rep, axis=1)
    out = _chunked_attention(qt, kt, vt, causal=False, sm_scale=scale,
                             q_offset=pos)
    return jnp.swapaxes(out, 1, 2)


def paged_attention(q, k_pages, v_pages, lengths, page_indices, **kw):
    """Decode-time KV-cache attention over paged KV (reference analog:
    masked_multihead_attention_kernel in fused_multi_transformer_op.cu.h:745).
    TPU: JAX Pallas paged_attention kernel. See also the framework's own
    ``ops/paged_attention.py::paged_decode_mha`` (same layout, runs in
    interpret mode too, integrates with inference.PagedKVCache).
    Quantized (int8) pools are NOT supported here — the stock kernel
    has no scale inputs; the serving engines' ``kv_dtype="int8"`` path
    uses ``paged_decode_mha``'s fused dequant instead."""
    from jax.experimental.pallas.ops.tpu.paged_attention import (
        paged_attention as _pa)

    return _pa(q, k_pages, v_pages, lengths, page_indices, **kw)


def grouped_matmul(lhs, rhs, group_sizes, preferred_element_type=jnp.float32):
    """MoE expert grouped GEMM (reference analog:
    phi/kernels/fusion/cutlass/moe_kernel.cu). TPU: megablox gmm kernel."""
    if _on_tpu():
        from jax.experimental.pallas.ops.tpu.megablox import gmm

        return gmm(lhs, rhs, group_sizes,
                   preferred_element_type=preferred_element_type)
    # fallback: segment-wise dense matmul
    starts = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                              jnp.cumsum(group_sizes)[:-1].astype(jnp.int32)])
    n_groups = rhs.shape[0]
    rows = lhs.shape[0]
    row_ids = jnp.arange(rows)
    seg = jnp.sum(row_ids[:, None] >= starts[None, :], axis=1) - 1
    seg = jnp.clip(seg, 0, n_groups - 1)
    picked = rhs[seg]  # [rows, K, N]
    return jnp.einsum("rk,rkn->rn", lhs, picked).astype(preferred_element_type)
