"""Pallas TPU kernels (placeholder module — kernels land with the kernel track).

The fused-op set the reference implements as hand-written CUDA
(fluid/operators/fused/, phi/kernels/fusion/) maps here as Pallas TPU
kernels. Until each kernel lands, callers fall back to XLA compositions.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def flash_attention(q, k, v, causal: bool = False):
    """[B, S, H, D] flash attention. Currently XLA composition; Pallas kernel
    replaces this body on TPU (see paddle_tpu/ops/pallas_kernels/)."""
    d = q.shape[-1]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / (d ** 0.5)
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)
