"""Creation ops (python/paddle/tensor/creation.py parity)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.tensor import Tensor, to_tensor
from ._helpers import diff_op, unwrap

__all__ = [
    "zeros",
    "ones",
    "full",
    "empty",
    "zeros_like",
    "ones_like",
    "full_like",
    "empty_like",
    "arange",
    "linspace",
    "logspace",
    "eye",
    "diag",
    "diagflat",
    "tril",
    "triu",
    "meshgrid",
    "assign",
    "clone",
    "to_tensor",
    "tril_indices",
    "triu_indices",
    "one_hot",
]


def _d(dtype, default=None):
    d = dtypes.convert_dtype(dtype)
    if d is None:
        d = default if default is not None else dtypes.get_default_dtype()
    return d


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(unwrap(s)) for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _d(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _d(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    fill_value = unwrap(fill_value)
    if dtype is None:
        return Tensor(jnp.full(_shape(shape), fill_value))
    return Tensor(jnp.full(_shape(shape), fill_value, _d(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(unwrap(x), dtype=dtypes.convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(unwrap(x), dtype=dtypes.convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(
        jnp.full_like(unwrap(x), unwrap(fill_value), dtype=dtypes.convert_dtype(dtype))
    )


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start, end, step = unwrap(start), unwrap(end), unwrap(step)
    if end is None:
        start, end = 0, start
    return Tensor(jnp.arange(start, end, step, dtype=dtypes.convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(
        jnp.linspace(unwrap(start), unwrap(stop), int(unwrap(num)), dtype=_d(dtype))
    )


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(
        jnp.logspace(
            unwrap(start), unwrap(stop), int(unwrap(num)), base=base, dtype=_d(dtype)
        )
    )


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_d(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    # taped via diff_op (like tril/triu): a bare Tensor(...) wrap here
    # silently dropped gradients (found by the r5 check_grad sweep)
    def _diag(v):
        if jnp.ndim(v) == 1 and padding_value != 0:
            base = jnp.full((v.shape[0] + abs(offset),) * 2, padding_value,
                            jnp.result_type(v))
            return base + jnp.diag(v - padding_value, k=offset)
        return jnp.diag(v, k=offset)

    return diff_op(_diag, "diag")(x)


def diagflat(x, offset=0, name=None):
    return diff_op(lambda v: jnp.diagflat(v, k=offset), "diagflat")(x)


def tril(x, diagonal=0, name=None):
    return diff_op(lambda v: jnp.tril(v, k=diagonal), "tril")(x)


def triu(x, diagonal=0, name=None):
    return diff_op(lambda v: jnp.triu(v, k=diagonal), "triu")(x)


def meshgrid(*args, **kwargs):
    # taped (r5 check_grad sweep: the bare Tensor wraps dropped grads)
    arrs = (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple))
            else args)
    return diff_op(lambda *vs: list(jnp.meshgrid(*vs, indexing="ij")),
                   "meshgrid")(*arrs)


def assign(x, output=None):
    if output is not None:
        output.set_value(jnp.asarray(unwrap(x)))
        return output
    return diff_op(lambda v: jnp.asarray(v), "assign")(x)


def clone(x, name=None):
    return diff_op(jnp.copy, "clone")(x)


def tril_indices(row, col, offset=0, dtype=dtypes.int64):
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r, c]).astype(dtypes.convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype=dtypes.int64):
    r, c = jnp.triu_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r, c]).astype(dtypes.convert_dtype(dtype)))


def one_hot(x, num_classes, name=None):
    import jax

    return Tensor(
        jax.nn.one_hot(unwrap(x), num_classes, dtype=dtypes.get_default_dtype())
    )


# ---- round-2 long tail (reference python/paddle/tensor/creation.py) --------


def complex(real, imag, name=None):
    import jax

    from ..core.autograd import apply_op

    return apply_op(lambda r, i: jax.lax.complex(r, i), real, imag,
                    op_name="complex")


def polar(abs, angle, name=None):
    """abs·e^{i·angle} (creation.py polar)."""
    import jax
    import jax.numpy as jnp

    from ..core.autograd import apply_op

    return apply_op(
        lambda a, t: jax.lax.complex(a * jnp.cos(t), a * jnp.sin(t)),
        abs, angle, op_name="polar")


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """paddle.create_parameter parity (creation.py create_parameter):
    a free-standing Parameter built through the same attr/initializer
    resolution as Layer.create_parameter."""
    from ..nn.layer.layers import Layer

    host = Layer(dtype=dtype)
    return host.create_parameter(list(shape), attr=attr, dtype=dtype,
                                 is_bias=is_bias,
                                 default_initializer=default_initializer)


def create_tensor(dtype, name=None, persistable=False):
    import jax.numpy as jnp

    from ..core.dtype import convert_dtype
    from ..core.tensor import Tensor

    return Tensor(jnp.zeros((0,), convert_dtype(dtype)))


__all__ += ["complex", "polar", "create_parameter", "create_tensor"]
