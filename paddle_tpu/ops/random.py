"""Random sampling ops (python/paddle/tensor/random.py parity)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.random import default_generator
from ..core.tensor import Tensor
from ._helpers import unwrap
from ..core.dtype import int64 as _i64

__all__ = [
    "rand",
    "randn",
    "randint",
    "randint_like",
    "randperm",
    "uniform",
    "normal",
    "standard_normal",
    "poisson",
    "bernoulli",
    "multinomial",
    "exponential_",
    "rand_like",
    "randn_like",
    "normal_like",
    "uniform_",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(unwrap(s)) for s in shape)


def _d(dtype):
    d = dtypes.convert_dtype(dtype)
    return d if d is not None else dtypes.get_default_dtype()


def rand(shape, dtype=None, name=None):
    k = default_generator.next_key()
    return Tensor(jax.random.uniform(k, _shape(shape), _d(dtype)))


def randn(shape, dtype=None, name=None):
    k = default_generator.next_key()
    return Tensor(jax.random.normal(k, _shape(shape), _d(dtype)))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    k = default_generator.next_key()
    return Tensor(
        jax.random.randint(k, _shape(shape), low, high, dtypes.convert_dtype(dtype))
    )


def randint_like(x, low=0, high=None, dtype=None, name=None):
    v = unwrap(x)
    d = dtypes.convert_dtype(dtype) or jnp.result_type(v)
    if high is None:
        low, high = 0, low
    k = default_generator.next_key()
    return Tensor(jax.random.randint(k, jnp.shape(v), low, high, d))


def randperm(n, dtype="int64", name=None):
    k = default_generator.next_key()
    return Tensor(jax.random.permutation(k, n).astype(dtypes.convert_dtype(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    k = default_generator.next_key() if seed == 0 else jax.random.PRNGKey(seed)
    return Tensor(
        jax.random.uniform(
            k, _shape(shape), _d(dtype), minval=unwrap(min), maxval=unwrap(max)
        )
    )


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    v = uniform(x.shape, x.dtype, min, max, seed)
    return x._inplace_(v._value)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    mean_v, std_v = unwrap(mean), unwrap(std)
    k = default_generator.next_key()
    if shape is None:
        shape = jnp.broadcast_shapes(jnp.shape(mean_v), jnp.shape(std_v))
    else:
        shape = _shape(shape)
    sample = jax.random.normal(k, shape, dtypes.get_default_dtype())
    return Tensor(sample * std_v + mean_v)


def normal_like(x, mean=0.0, std=1.0, name=None):
    return normal(mean, std, jnp.shape(unwrap(x)))


def rand_like(x, dtype=None, name=None):
    return rand(x.shape, dtype or x.dtype)


def randn_like(x, dtype=None, name=None):
    return randn(x.shape, dtype or x.dtype)


def poisson(x, name=None):
    k = default_generator.next_key()
    return Tensor(
        jax.random.poisson(k, unwrap(x)).astype(jnp.result_type(unwrap(x)))
    )


def bernoulli(x, name=None):
    k = default_generator.next_key()
    v = unwrap(x)
    return Tensor(
        jax.random.bernoulli(k, v).astype(jnp.result_type(v))
    )


def multinomial(x, num_samples=1, replacement=False, name=None):
    k = default_generator.next_key()
    v = unwrap(x)
    logits = jnp.log(jnp.maximum(v, 1e-38))
    if replacement:
        # sample along a leading axis then move it last: (*batch, num_samples)
        out = jax.random.categorical(
            k, logits, axis=-1, shape=(num_samples, *v.shape[:-1])
        )
        out = jnp.moveaxis(out, 0, -1)
        if v.ndim == 1:
            out = out.reshape(num_samples)
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(k, v.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(_i64))


def exponential_(x, lam=1.0, name=None):
    k = default_generator.next_key()
    v = unwrap(x)
    sample = jax.random.exponential(k, jnp.shape(v), jnp.result_type(v)) / lam
    return x._inplace_(sample)
