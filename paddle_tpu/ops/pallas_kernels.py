"""Hand-written Pallas TPU kernels for the reference's fused-op set.

Reference north-star kernels (SURVEY.md §2.2 fused ops):
- fused_rms_norm / fused_layer_norm ≙ fused_bias_dropout_residual_layer_norm
  (operators/fused/fused_bias_dropout_residual_layer_norm_op.cu,
   fused_layernorm_residual_dropout_bias.h)
- fused_rope ≙ fused_rotary_position_embedding (phi fusion/gpu/fused_rope_kernel.cu:87)
- fused_linear_param_grad_add (phi fusion fused_linear_param_grad_add_kernel.cu)
- decode_mha ≙ masked_multihead_attention_kernel decode-time MHA over a KV
  cache (fused_multi_transformer_op.cu.h:745)

Design: each kernel is a `pl.pallas_call` tiled for VMEM with the row/lane
constraints from the TPU tiling table (last dim 128-aligned blocks where it
matters); off-TPU the SAME kernel runs in interpreter mode so CPU tests
exercise the real kernel code path, not a separate fallback. fp32 accumulation
throughout; bf16 in/out supported.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# pltpu ships with every jax build (memory-space enums and scratch shapes
# work under interpret mode too) — import unconditionally so kernels can use
# SMEM operands and VMEM scratch without per-call-site fallbacks
from jax.experimental.pallas import tpu as pltpu

_VMEM = pltpu.VMEM

__all__ = ["rms_norm", "fused_layer_norm", "fused_rope", "decode_mha",
           "fused_linear_param_grad_add"]


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _row_block(n_rows: int, target: int = 256) -> int:
    b = min(n_rows, target)
    while n_rows % b:
        b -= 1
    return max(b, 1)


# ---------------------------------------------------------------------------
# RMSNorm (Llama hot path)
# ---------------------------------------------------------------------------


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(ms + eps)
    o_ref[...] = (y * w_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _rms_fwd_impl(x, weight, eps):
    shape = x.shape
    h = shape[-1]
    x2 = x.reshape(-1, h)
    rb = _row_block(x2.shape[0])
    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x.dtype),
        grid=(x2.shape[0] // rb,),
        in_specs=[pl.BlockSpec((rb, h), lambda i: (i, 0)),
                  pl.BlockSpec((h,), lambda i: (0,))],
        out_specs=pl.BlockSpec((rb, h), lambda i: (i, 0)),
        interpret=_interpret(),
    )(x2, weight)
    return out.reshape(shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms_norm(x, weight, eps):
    return _rms_fwd_impl(x, weight, eps)


def _rms_vjp_fwd(x, weight, eps):
    return _rms_fwd_impl(x, weight, eps), (x, weight)


def _rms_vjp_bwd(eps, res, g):
    # pallas fwd, XLA bwd: out = x·r·w with r = rsqrt(mean(x²)+eps);
    # dx = w·g·r − x·r³/H·Σ(g·w·x);  dw = Σ_rows g·x·r
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    h = xf.shape[-1]
    r = jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + eps)
    gw = gf * wf
    dx = gw * r - xf * (r ** 3 / h) * jnp.sum(gw * xf, -1, keepdims=True)
    dw = jnp.sum((gf * xf * r).reshape(-1, h), axis=0)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_rms_norm.defvjp(_rms_vjp_fwd, _rms_vjp_bwd)


@functools.partial(jax.jit, static_argnames=("eps",))
def rms_norm(x, weight, eps: float = 1e-6):
    """y = x / sqrt(mean(x², -1) + eps) * w. x: [..., H]. Differentiable
    (custom VJP: Pallas forward, XLA backward)."""
    return _rms_norm(x, weight, eps)


# ---------------------------------------------------------------------------
# Fused bias + residual + LayerNorm  (dropout composed outside under jit —
# XLA fuses the mask multiply into this kernel's input)
# ---------------------------------------------------------------------------


def _ln_kernel(x_ref, r_ref, b_ref, g_ref, beta_ref, o_ref, *, eps,
               has_resid, has_bias):
    x = x_ref[...].astype(jnp.float32)
    if has_bias:
        x = x + b_ref[...].astype(jnp.float32)
    if has_resid:
        x = x + r_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    y = y * g_ref[...].astype(jnp.float32) + beta_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def _ln_fwd_impl(x, residual, bias, gamma, beta, eps):
    shape = x.shape
    h = shape[-1]
    x2 = x.reshape(-1, h)
    n = x2.shape[0]
    rb = _row_block(n)
    has_resid = residual is not None
    has_bias = bias is not None
    r2 = residual.reshape(-1, h) if has_resid else jnp.zeros((1, h), x.dtype)
    b = bias if has_bias else jnp.zeros((h,), x.dtype)
    out = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps, has_resid=has_resid,
                          has_bias=has_bias),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x.dtype),
        grid=(n // rb,),
        in_specs=[
            pl.BlockSpec((rb, h), lambda i: (i, 0)),
            (pl.BlockSpec((rb, h), lambda i: (i, 0)) if has_resid
             else pl.BlockSpec((1, h), lambda i: (0, 0))),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((rb, h), lambda i: (i, 0)),
        interpret=_interpret(),
    )(x2, r2, b, gamma, beta)
    return out.reshape(shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _fused_ln(x, residual, bias, gamma, beta, eps):
    return _ln_fwd_impl(x, residual, bias, gamma, beta, eps)


def _ln_vjp_fwd(x, residual, bias, gamma, beta, eps):
    return (_ln_fwd_impl(x, residual, bias, gamma, beta, eps),
            (x, residual, bias, gamma))


def _ln_vjp_bwd(eps, res, g):
    x, residual, bias, gamma = res
    shape = x.shape
    h = shape[-1]
    z = x.astype(jnp.float32)
    if bias is not None:
        z = z + bias.astype(jnp.float32)
    if residual is not None:
        z = z + residual.astype(jnp.float32)
    mu = jnp.mean(z, -1, keepdims=True)
    zc = z - mu
    rstd = jax.lax.rsqrt(jnp.mean(zc * zc, -1, keepdims=True) + eps)
    xhat = zc * rstd
    gf = g.astype(jnp.float32)
    dgamma = jnp.sum((gf * xhat).reshape(-1, h), axis=0)
    dbeta_full = jnp.sum(gf.reshape(-1, h), axis=0)
    dxhat = gf * gamma.astype(jnp.float32)
    dz = rstd * (dxhat - jnp.mean(dxhat, -1, keepdims=True)
                 - xhat * jnp.mean(dxhat * xhat, -1, keepdims=True))
    dx = dz.astype(x.dtype)
    dresid = dz.astype(residual.dtype) if residual is not None else None
    dbias = (jnp.sum(dz.reshape(-1, h), axis=0).astype(bias.dtype)
             if bias is not None else None)
    return (dx, dresid, dbias, dgamma.astype(gamma.dtype),
            dbeta_full.astype(gamma.dtype))


_fused_ln.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


@functools.partial(jax.jit, static_argnames=("eps",))
def fused_layer_norm(x, residual=None, bias=None, gamma=None, beta=None,
                     eps: float = 1e-5):
    """LN(x [+ bias] [+ residual]) * gamma + beta — the core of the
    reference's fused_bias_dropout_residual_layer_norm. Differentiable
    (Pallas forward, XLA backward)."""
    h = x.shape[-1]
    if gamma is None:
        gamma = jnp.ones((h,), x.dtype)
    if beta is None:
        beta = jnp.zeros((h,), x.dtype)
    return _fused_ln(x, residual, bias, gamma, beta, eps)


# ---------------------------------------------------------------------------
# Rotary position embedding (NeoX interleaved-halves convention, matching
# the reference fused_rope_kernel.cu:87 use_neox_rotary_style)
# ---------------------------------------------------------------------------


def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref):
    """Roll-form rotation: out = x·C + roll(x, D/2)·S with C = [cos|cos],
    S = [-sin|sin] — one multiply-add pass, no lane-dim split/concat (the
    half-slice forms relayout the 128-lane head_dim twice)."""
    x = x_ref[...].astype(jnp.float32)          # [1, bs_rows, H, D]
    c = cos_ref[...].astype(jnp.float32)[..., None, :]  # [1, bs, 1, D]
    s = sin_ref[...].astype(jnp.float32)[..., None, :]
    d2 = x.shape[-1] // 2
    xr = pltpu.roll(x, d2, 3) if pltpu is not None and not _interpret() \
        else jnp.roll(x, d2, axis=-1)
    o_ref[...] = (x * c + xr * s).astype(o_ref.dtype)


def _rope_impl(x, cos, sin):
    b_, s_, h_, d_ = x.shape
    # full-width tables: C = [cos|cos], S = [-sin|sin]; with roll(x, d2)
    # this reproduces (x1·c − x2·s | x2·c + x1·s)
    cos_f = jnp.concatenate([cos, cos], axis=-1)
    sin_f = jnp.concatenate([-sin, sin], axis=-1)
    cos_b = jnp.broadcast_to(cos_f[None], (b_, s_, d_))
    sin_b = jnp.broadcast_to(sin_f[None], (b_, s_, d_))
    sb = _row_block(s_, 512)
    out = pl.pallas_call(
        _rope_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=(b_, s_ // sb),
        in_specs=[
            pl.BlockSpec((1, sb, h_, d_), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, sb, d_), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sb, d_), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, sb, h_, d_), lambda i, j: (i, j, 0, 0)),
        interpret=_interpret(),
    )(x, cos_b, sin_b)
    return out


@jax.custom_vjp
def _rope(x, cos, sin):
    return _rope_impl(x, cos, sin)


def _rope_vjp_fwd(x, cos, sin):
    return _rope_impl(x, cos, sin), (cos, sin)


def _rope_vjp_bwd(res, g):
    # rotation transpose = rotation by −θ: reuse the SAME kernel with −sin
    cos, sin = res
    dx = _rope_impl(g, cos, -sin)
    return dx, jnp.zeros_like(cos), jnp.zeros_like(sin)


_rope.defvjp(_rope_vjp_fwd, _rope_vjp_bwd)


@jax.jit
def fused_rope(x, cos, sin):
    """Apply rotary embedding. x: [B, S, H, D]; cos/sin: [S, D/2].
    Differentiable (the VJP reuses the kernel with −sin)."""
    return _rope(x, cos, sin)


# ---------------------------------------------------------------------------
# Decode-time MHA over a KV cache (one query token per sequence)
# ---------------------------------------------------------------------------


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref,
                   l_ref, *, scale, block_s):
    """Online-softmax decode step over one S-block of the KV cache.

    Grid (B, nS) — S innermost, accumulated in VMEM scratch so arbitrarily
    long caches stream through a bounded working set (round-1 version loaded
    the whole [S, H, D] slab per batch row and spilled at 7B+ shapes).
    """
    ib, js = pl.program_id(0), pl.program_id(1)
    ns = pl.num_programs(1)

    @pl.when(js == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    ln = len_ref[ib]

    # skip blocks entirely past the valid length
    @pl.when(js * block_s < ln)
    def _compute():
        # decode is HBM-bandwidth-bound: all math is VPU-shaped (no batched
        # dots), keeping the cache streaming at full rate. Layout (bs, H):
        # per-head softmax reduces over sublanes, heads stay in lanes.
        q = q_ref[0].astype(jnp.float32)            # [H, D]
        k = k_ref[0].astype(jnp.float32)            # [bs, H, D]
        v = v_ref[0].astype(jnp.float32)
        s = jnp.sum(q[None] * k, axis=-1) * scale   # [bs, H]
        pos = js * block_s + jax.lax.broadcasted_iota(
            jnp.int32, (block_s, 1), 0)
        mask = pos < ln                             # [bs, 1]
        s = jnp.where(mask, s, -1e30)
        m_prev = m_ref[...]                         # [1, H]
        m_cur = jnp.max(s, axis=0, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                      # [bs, H]
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)             # [1, H]
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=0, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = (acc_ref[...] * jnp.transpose(alpha)
                        + jnp.sum(p[:, :, None] * v, axis=0))  # [H, D]

    @pl.when(js == ns - 1)
    def _finalize():
        l_safe = jnp.maximum(jnp.transpose(l_ref[...]), 1e-30)  # [H, 1]
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def decode_mha(q, k_cache, v_cache, seq_lens, block_s=None):
    """Single-step decode attention (≙ masked_multihead_attention_kernel,
    fused_multi_transformer_op.cu.h:745).

    q: [B, H, D] (this step's query) — k/v_cache: [B, S, H, D] — seq_lens:
    [B] valid lengths (the new token's k/v must already be written at
    position seq_lens-1). Returns [B, H, D]. The cache streams through VMEM
    in S-blocks with online-softmax accumulation (flash recurrence), so
    S is bounded by HBM, not VMEM. ``block_s=None`` consults the autotune
    cache (experiments/exp_autotune_sweep.py populates it), default 512.
    """
    if block_s is None:
        from .autotune import decode_signature, lookup

        tuned = lookup("decode_mha", decode_signature(
            k_cache.shape[1], q.shape[1], q.shape[2],
            jnp.dtype(q.dtype).name)) or {}
        block_s = tuned.get("block_s", 512)
    return _decode_mha_jit(q, k_cache, v_cache, seq_lens, block_s)


@functools.partial(jax.jit, static_argnums=(4,))
def _decode_mha_jit(q, k_cache, v_cache, seq_lens, block_s):
    b_, h_, d_ = q.shape
    s_max = k_cache.shape[1]
    scale = 1.0 / math.sqrt(d_)
    bs = _row_block(s_max, block_s)
    return pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, block_s=bs),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(b_, s_max // bs),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, h_, d_), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, bs, h_, d_), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, bs, h_, d_), lambda i, j: (i, j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h_, d_), lambda i, j: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h_, d_), jnp.float32),
            pltpu.VMEM((1, h_), jnp.float32),
            pltpu.VMEM((1, h_), jnp.float32),
        ],
        interpret=_interpret(),
    )(seq_lens, q, k_cache, v_cache)


# ---------------------------------------------------------------------------
# fused_linear_param_grad_add: dW += xᵀ·dy (fp32 accum, in-place on dW)
# ---------------------------------------------------------------------------


def _grad_add_kernel(x_ref, dy_ref, dw_ref, o_ref, acc_ref):
    """One (K-block, N-block) output tile accumulated over T-blocks.

    Grid (nK, nN, nT) — T innermost; the fp32 accumulator lives in VMEM
    scratch, the prior dweight value is folded in at the first T step, and
    the tile is written once at the last (round-1 version mapped whole
    operands into VMEM with no grid and spilled at 4096x11008 fp32)."""
    it = pl.program_id(2)

    @pl.when(it == 0)
    def _init():
        acc_ref[...] = dw_ref[...]

    x = x_ref[...]
    dy = dy_ref[...]
    acc_ref[...] += jax.lax.dot_general(
        x, dy, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(it == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[...] = acc_ref[...]


@jax.jit
def fused_linear_param_grad_add(x, dy, dweight):
    """dweight(fp32) += xᵀ @ dy — the reference's main-grad accumulation
    kernel (fused_linear_param_grad_add_kernel.cu): bf16 activations/grad,
    fp32 accumulator, single fused pass, aliased in-place output. Tiled
    over (K, N, T) so 7B-scale weights (e.g. 4096x11008) accumulate through
    a bounded VMEM working set."""
    x2 = x.reshape(-1, x.shape[-1])
    dy2 = dy.reshape(-1, dy.shape[-1])
    kdim, ndim = dweight.shape
    tdim = x2.shape[0]
    bk = _row_block(kdim, 512)
    bn = _row_block(ndim, 512)
    bt = _row_block(tdim, 512)
    if not _interpret() and (bt % 128 or bk % 128 or bn % 128) \
            and (x2.dtype != jnp.float32 or dy2.dtype != jnp.float32):
        # Mosaic rejects bf16 matmuls at sub-lane-multiple tile dims
        # ("Bad lhs type"); fp32 compiles — real training shapes are
        # 128-multiples and keep the bf16 MXU path
        x2 = x2.astype(jnp.float32)
        dy2 = dy2.astype(jnp.float32)
    dw32 = dweight.astype(jnp.float32)
    return pl.pallas_call(
        _grad_add_kernel,
        out_shape=jax.ShapeDtypeStruct(dweight.shape, jnp.float32),
        grid=(kdim // bk, ndim // bn, tdim // bt),
        in_specs=[
            pl.BlockSpec((bt, bk), lambda i, j, t: (t, i)),
            pl.BlockSpec((bt, bn), lambda i, j, t: (t, j)),
            pl.BlockSpec((bk, bn), lambda i, j, t: (i, j)),
        ],
        out_specs=pl.BlockSpec((bk, bn), lambda i, j, t: (i, j)),
        scratch_shapes=[pltpu.VMEM((bk, bn), jnp.float32)],
        input_output_aliases={2: 0},
        interpret=_interpret(),
    )(x2, dy2, dw32)
