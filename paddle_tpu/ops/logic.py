"""Comparison / logical ops (python/paddle/tensor/logic.py parity). All
outputs are non-differentiable."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ._helpers import nondiff_op, unwrap

__all__ = [
    "equal",
    "not_equal",
    "greater_than",
    "greater_equal",
    "less_than",
    "less_equal",
    "equal_all",
    "allclose",
    "isclose",
    "logical_and",
    "logical_or",
    "logical_xor",
    "logical_not",
    "bitwise_and",
    "bitwise_or",
    "bitwise_xor",
    "bitwise_not",
    "isnan",
    "isinf",
    "isfinite",
    "is_empty",
    "isin",
]

_BINARY = dict(
    equal=jnp.equal,
    not_equal=jnp.not_equal,
    greater_than=jnp.greater,
    greater_equal=jnp.greater_equal,
    less_than=jnp.less,
    less_equal=jnp.less_equal,
    logical_and=jnp.logical_and,
    logical_or=jnp.logical_or,
    logical_xor=jnp.logical_xor,
    bitwise_and=jnp.bitwise_and,
    bitwise_or=jnp.bitwise_or,
    bitwise_xor=jnp.bitwise_xor,
)
for _n, _f in _BINARY.items():
    globals()[_n] = nondiff_op(_f, _n)

_UNARY = dict(
    logical_not=jnp.logical_not,
    bitwise_not=jnp.bitwise_not,
    isnan=jnp.isnan,
    isinf=jnp.isinf,
    isfinite=jnp.isfinite,
)
for _n, _f in _UNARY.items():
    globals()[_n] = nondiff_op(_f, _n)


def equal_all(x, y, name=None):
    return Tensor(jnp.array_equal(unwrap(x), unwrap(y)))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(
        jnp.allclose(unwrap(x), unwrap(y), rtol=rtol, atol=atol, equal_nan=equal_nan)
    )


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(
        jnp.isclose(unwrap(x), unwrap(y), rtol=rtol, atol=atol, equal_nan=equal_nan)
    )


def is_empty(x, name=None):
    return Tensor(jnp.asarray(jnp.size(unwrap(x)) == 0))


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return Tensor(jnp.isin(unwrap(x), unwrap(test_x), invert=invert))


def is_complex(x, name=None):
    import jax.numpy as jnp

    from ._helpers import unwrap

    return jnp.iscomplexobj(unwrap(x))


def is_floating_point(x, name=None):
    import jax.numpy as jnp
    import numpy as np

    from ._helpers import unwrap

    return bool(np.issubdtype(np.dtype(unwrap(x).dtype), np.floating)
                or unwrap(x).dtype == jnp.bfloat16)


def is_integer(x, name=None):
    import numpy as np

    from ._helpers import unwrap

    return bool(np.issubdtype(np.dtype(unwrap(x).dtype), np.integer))


__all__ += ["is_complex", "is_floating_point", "is_integer"]
