"""GQA-native flash attention, forward + backward, as Pallas TPU kernels.

This is the framework's own flash kernel (replacing the stock
``jax.experimental.pallas.ops.tpu.flash_attention`` routing of round 1).
Reference analog: ``phi/kernels/gpu/flash_attn_kernel.cu:213`` (fwd) and
``flash_attn_grad_kernel.cu`` (bwd) which dynload libflashattn; here the
same online-softmax tiling is expressed for the MXU/VMEM machine model.

Design points (and why they differ from the stock JAX kernel):

- **Compact residuals.** The only saved values are the output and a
  log-sum-exp per row stored as ``[B, H, S]`` fp32.  The stock kernel keeps
  separate ``m``/``l`` tensors padded to a 128-lane trailing dim —
  ``f32[B, H, S, 128]`` each — which is exactly the HLO-temp blow-up that
  OOMed round 1's benchmark.
- **GQA in the index maps.** Q may have ``Hq = G * Hkv`` heads; K/V blocks
  are selected with ``h // G`` so grouped heads share KV *without*
  materialising ``jnp.repeat``-ed keys (the reference handles GQA inside
  libflashattn the same way).
- **In-kernel dropout.** A counter-based hash RNG (murmur3 finalizer over
  ``(seed, batch, head, q, k)``) generates the keep-mask inside the kernel,
  identically in forward and both backward kernels, so dropout costs no
  extra memory and no second attention pass.  (``pltpu.prng_*`` is not used
  because it has no interpret-mode lowering — the hash runs everywhere.)
- **Bottom-right causal alignment**: query ``i`` attends keys
  ``<= i + (Sk - Sq)`` — the decode-with-KV-cache convention used across
  this repo (see ``ops/pallas.py::_chunked_attention``).  Fully-masked
  blocks are skipped via ``pl.when``.

Layout: ``[B, H, S, D]`` (callers transpose from paddle's ``[B, S, H, D]``).
fp32 accumulation throughout; bf16 in/out supported.
"""
from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu memory spaces; interpret mode needs pl only
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
    _SMEM_SPEC = pl.BlockSpec(memory_space=pltpu.SMEM)
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None
    _SMEM_SPEC = None

__all__ = ["flash_attention_bhsd", "supports"]

_NEG_INF = -1e30  # large-negative mask value; avoids inf-inf NaNs


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _pick_block(s: int, target: int, interpret: bool) -> Optional[int]:
    """Largest divisor of s that is <= target and (on real TPU) a multiple
    of 128 sublanes; None if no usable block exists."""
    if interpret:
        from .pallas_kernels import _row_block

        return _row_block(s, target)
    for b in (target, 1024, 512, 256, 128):
        if b <= target and s % b == 0:
            return b
    return None


def supports(sq: int, sk: int, interpret: Optional[bool] = None) -> bool:
    """Whether the Pallas kernel can handle these sequence lengths."""
    it = _interpret() if interpret is None else interpret
    return (_pick_block(sq, 1024, it) is not None
            and _pick_block(sk, 1024, it) is not None)


def _sublane_plan(d: int, dtype, interpret: bool):
    """Mosaic (v5e libtpu) rejects bf16 dots whose CONTRACTION dim is not
    a lane multiple ("Bad lhs type" on the D-contracting q·kᵀ / dO·vᵀ
    dots when D % 128 != 0, found on-chip 2026-07-31).  Returns
    ``(mode, dpad)``:

    - ``(None, d)``  — native path, nothing to do (D already a lane
      multiple, fp32 input, or interpret mode).
    - ``('pad', dp)``  — zero-pad D to ``dp`` OUTSIDE the kernel: the
      kernel then runs the exact D=128 bf16 shapes that were on-chip
      green from the start.  Full-rate bf16 MXU dots; costs ~2x q/k/v/o
      HBM bytes at D=64.  The default.
    - ``('kpad', dp)`` — zero-pad INSIDE the kernel (VMEM concat after
      load, slice before store): same full-rate dots with NO extra HBM
      traffic, but needs Mosaic's in-kernel concatenate lowering — run
      the staged on-chip parity check before trusting it on hardware.
    - ``('fp32', d)``  — the r4 guard: upcast everything to fp32
      (compiles everywhere, but fp32 dots run at a fraction of bf16
      MXU rate on the hottest kernel).  Escape hatch.

    Select via ``PADDLE_TPU_FLASH_SUBLANE`` (pad|kpad|fp32).  Padding
    with zeros is exact: zero lanes contribute 0 to every D-contraction,
    and the padded tail of each output is sliced off (fwd) or provably
    zero (grads).

    ``PADDLE_TPU_FLASH_SUBLANE_FORCE=1`` applies the plan in interpret
    mode too — that is how the CPU suite exercises the pad/kpad numerics
    the device path will run.

    PROCESS-LIFETIME BINDING: the env var is read at TRACE time and the
    chosen mode is frozen into the cached jit program for each
    (shape, dtype) signature. Changing ``PADDLE_TPU_FLASH_SUBLANE``
    after a shape has compiled silently has NO effect on that shape for
    the rest of the process, and two modes cannot coexist for the same
    shape — set the env var before the first flash call and leave it.
    When the monitor is enabled, every selection is recorded as
    ``paddle_tpu_flash_sublane_mode_total{mode=...}`` so a mid-process
    mismatch between the env var and the compiled programs is visible
    in the metrics instead of silent.
    """
    force = os.environ.get("PADDLE_TPU_FLASH_SUBLANE_FORCE") == "1"
    if ((interpret and not force) or d % 128 == 0
            or jnp.dtype(dtype) == jnp.float32):
        return None, d
    mode = os.environ.get("PADDLE_TPU_FLASH_SUBLANE", "pad")
    if mode not in ("pad", "kpad", "fp32"):
        raise ValueError(
            f"PADDLE_TPU_FLASH_SUBLANE={mode!r}: expected pad|kpad|fp32")
    _record_sublane_mode(mode)
    return mode, -(-d // 128) * 128


def _record_sublane_mode(mode: str) -> None:
    """Publish the sublane plan frozen into this trace (monitor label;
    runs at trace time only, never per step)."""
    try:
        from .. import monitor

        if monitor.enabled():
            monitor.counter(
                "paddle_tpu_flash_sublane_mode_total",
                "flash-attention sublane plans frozen into compiled "
                "programs, by mode (process-lifetime env binding)",
                ("mode",)).labels(mode=mode).inc()
    except Exception:  # metrics must never break a kernel trace
        pass


def _pad_d(x, dpad: int):
    """Zero-pad the trailing (head) dim to ``dpad`` lanes."""
    if x.shape[-1] == dpad:
        return x
    return jnp.concatenate(
        [x, jnp.zeros(x.shape[:-1] + (dpad - x.shape[-1],), x.dtype)],
        axis=-1)


# ---------------------------------------------------------------------------
# Counter-based RNG for dropout (murmur3 finalizer)
# ---------------------------------------------------------------------------


def _mix(x):
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _keep_mask(seed, b, h, q0, k0, bq, bk, dropout_p):
    """Boolean keep-mask for the (bq, bk) score block whose top-left element
    is global (q0, k0). Deterministic in (seed, b, h, global q, global k)."""
    s0 = _mix(seed.astype(jnp.uint32)
              ^ (b.astype(jnp.uint32) * jnp.uint32(0x9E3779B9))
              ^ (h.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)))
    qi = (q0.astype(jnp.uint32)
          + jax.lax.broadcasted_iota(jnp.uint32, (bq, bk), 0))
    ki = (k0.astype(jnp.uint32)
          + jax.lax.broadcasted_iota(jnp.uint32, (bq, bk), 1))
    bits = _mix(_mix(qi + s0) ^ ki)
    thresh = jnp.uint32(min(int(dropout_p * 4294967296.0), 4294967295))
    return bits >= thresh  # P(keep) = 1 - dropout_p


def _causal_valid(iq, ik, block_q, block_k, offset):
    """Bottom-right-aligned validity for the (iq, ik) score block: query i
    attends keys <= i + offset. Shared by fwd and both bwd kernels so the
    alignment convention can never diverge between them."""
    qpos = (iq * block_q
            + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0))
    kpos = (ik * block_k
            + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1))
    return kpos <= qpos + offset


def _apply_causal_mask(s, causal, iq, ik, block_q, block_k, offset,
                       lead_batch: bool = False):
    """Causal masking for a score block (``s`` is (bq, bk), or (H, bq, bk)
    with ``lead_batch``), SPECIALIZED to diagonal blocks: blocks entirely
    below the causal boundary skip the iota/compare/select passes (at
    1024x1024 those are 3 extra VPU sweeps — most blocks of a long-
    sequence causal kernel are fully valid). Shared by the per-head AND
    head-batched kernels so the alignment convention cannot diverge.

    Returns (s, valid); valid is non-None only when offset < 0, the one
    case where rows can be globally all-masked and the caller must re-mask
    probabilities (there the mask is applied unconditionally — the valid
    matrix is needed anyway, so the cond would buy nothing)."""
    if not causal:
        return s, None

    def mask(x):
        v = _causal_valid(iq, ik, block_q, block_k, offset)
        return jnp.where(v[None] if lead_batch else v, x, _NEG_INF), v

    if offset < 0:
        s, v = mask(s)
        return s, (v[None] if lead_batch else v)
    # does this block contain ANY masked entry? (bottom-right alignment:
    # the block's last key position vs its first query's boundary)
    is_diag = (ik * block_k + block_k - 1) > (iq * block_q + offset)
    s = jax.lax.cond(is_diag, lambda x: mask(x)[0], lambda x: x, s)
    return s, None


def _block_scores(q, k, sm_scale, causal, iq, ik, block_q, block_k, offset):
    """Masked fp32 score block for the per-head kernel."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale
    return _apply_causal_mask(s, causal, iq, ik, block_q, block_k, offset)


def _dropped(p, seed, b, h, iq, ik, block_q, block_k, dropout_p):
    """p with the dropout keep-mask applied and 1/(1-p) upscaling — the
    SAME mask in fwd and both bwd kernels (hash of global coordinates)."""
    keep = _keep_mask(seed, b, h, iq * block_q, ik * block_k,
                      block_q, block_k, dropout_p)
    return jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout_p))


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, sm_scale, causal, dropout_p,
                offset, block_q, block_k, dpad):
    b, h, iq, ik = (pl.program_id(i) for i in range(4))
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _compute():
        q = _pad_d(q_ref[0, 0], dpad)        # (bq, Dp)
        k = _pad_d(k_ref[0, 0], dpad)        # (bk, Dp)
        v = _pad_d(v_ref[0, 0], dpad)
        s, valid = _block_scores(q, k, sm_scale, causal, iq, ik,
                                 block_q, block_k, offset)
        # single-column running stats: alpha's exp runs on (bq, 1), not the
        # (bq, 128) replicated buffer — transcendentals are the VPU cost
        m_prev = m_ref[:, 0:1]               # (bq, 1)
        l_prev = l_ref[:, 0:1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)      # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        if valid is not None:
            # offset < 0 only: globally all-masked rows have m_new == -inf
            # and exp(0) == 1 garbage; offset >= 0 needs no re-mask — the
            # masked s give exp(-1e30 - finite) == 0 exactly
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)                 # (bq, 1)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_p > 0.0:
            # l accumulates UNdropped p (softmax normalizer is exact); only
            # the value contraction sees the mask, pre-scaled by 1/(1-p)
            pv = _dropped(p, seed_ref[0], b, h, iq, ik, block_q, block_k,
                          dropout_p)
        else:
            pv = p
        acc_ref[...] = (acc_ref[...] * alpha
                        + jax.lax.dot_general(
                            pv.astype(v.dtype), v,
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32))
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        needed = ik * block_k <= iq * block_q + block_q - 1 + offset
        pl.when(needed)(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_ref[:, 0:1]
        l_safe = jnp.maximum(l, 1e-30)
        # [:, :D] is a no-op unless dpad padded the accumulator (kpad)
        o_ref[0, 0] = ((acc_ref[...] / l_safe)[:, :o_ref.shape[-1]]
                       .astype(o_ref.dtype))
        lse_ref[0, 0] = m_ref[:, 0:1] + jnp.log(l_safe)  # (bq, 1)


def _fwd_impl(q, k, v, seed, causal, sm_scale, dropout_p, block_q, block_k,
              interpret):
    in_dtype = q.dtype
    d_orig = q.shape[-1]
    mode, dp = _sublane_plan(d_orig, in_dtype, interpret)
    if mode == "fp32":
        q, k, v = (x.astype(jnp.float32) for x in (q, k, v))
    elif mode == "pad":
        q, k, v = (_pad_d(x, dp) for x in (q, k, v))
    bsz, hq, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    group = hq // hkv
    bq = _pick_block(sq, block_q, interpret)
    bk = _pick_block(sk, block_k, interpret)
    nq, nk = sq // bq, sk // bk
    offset = sk - sq
    dpad = dp if mode == "kpad" else d
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          dropout_p=dropout_p, offset=offset,
                          block_q=bq, block_k=bk, dpad=dpad),
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((bsz, hq, sq, 1), jnp.float32)],
        grid=(bsz, hq, nq, nk),
        in_specs=[
            _SMEM_SPEC,
            pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b, h, i, j, g=group: (b, h // g, j, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b, h, i, j, g=group: (b, h // g, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        scratch_shapes=[
            _VMEM((bq, dpad), jnp.float32),
            _VMEM((bq, 128), jnp.float32),
            _VMEM((bq, 128), jnp.float32),
        ],
        interpret=interpret,
    )(seed, q, k, v)
    if mode == "pad":
        out = out[..., :d_orig]
    elif mode == "fp32":
        out = out.astype(in_dtype)
    return out, lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, acc_ref, *, sm_scale, causal, dropout_p, offset,
                   block_q, block_k, dpad):
    b, h, iq, ik = (pl.program_id(i) for i in range(4))
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _compute():
        q = _pad_d(q_ref[0, 0], dpad)
        k = _pad_d(k_ref[0, 0], dpad)
        v = _pad_d(v_ref[0, 0], dpad)
        do = _pad_d(do_ref[0, 0], dpad)
        lse = lse_ref[0, 0]                             # (bq, 1)
        delta = delta_ref[0, 0]
        s, valid = _block_scores(q, k, sm_scale, causal, iq, ik,
                                 block_q, block_k, offset)
        p = jnp.exp(s - lse)                            # normalized probs
        if causal and offset < 0:
            # offset >= 0 guarantees every row saw >= 1 valid key, so lse
            # is finite and masked scores give exp(-1e30 - lse) == 0 with
            # no re-mask; offset < 0 has all-masked rows (lse ~ -1e30,
            # exp(~0) = 1) that must be zeroed explicitly
            p = jnp.where(valid, p, 0.0)
        dpd = jax.lax.dot_general(                      # dO @ V^T
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            pd = _dropped(p, seed_ref[0], b, h, iq, ik, block_q, block_k,
                          dropout_p)
            ds = pd * dpd - p * delta
        else:
            ds = p * (dpd - delta)
        acc_ref[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

    if causal:
        needed = ik * block_k <= iq * block_q + block_q - 1 + offset
        pl.when(needed)(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0, 0] = (acc_ref[...][:, :dq_ref.shape[-1]]
                        .astype(dq_ref.dtype))


def _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, sm_scale, causal,
                    dropout_p, offset, block_q, block_k, group, dpad):
    b, hkv, ik, g, iq = (pl.program_id(i) for i in range(5))
    nq = pl.num_programs(4)
    h = hkv * group + g

    @pl.when((g == 0) & (iq == 0))
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _compute():
        q = _pad_d(q_ref[0, 0], dpad)
        k = _pad_d(k_ref[0, 0], dpad)
        v = _pad_d(v_ref[0, 0], dpad)
        do = _pad_d(do_ref[0, 0], dpad)
        lse = lse_ref[0, 0]                             # (bq, 1)
        delta = delta_ref[0, 0]
        s, valid = _block_scores(q, k, sm_scale, causal, iq, ik,
                                 block_q, block_k, offset)
        p = jnp.exp(s - lse)  # masked s → exp(-1e30 - lse) == 0 (offset>=0)
        if causal and offset < 0:
            p = jnp.where(valid, p, 0.0)  # all-masked rows: lse ~ -1e30
        dpd = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            pd = _dropped(p, seed_ref[0], b, h, iq, ik, block_q, block_k,
                          dropout_p)
            ds = pd * dpd - p * delta
        else:
            pd = p
            ds = p * (dpd - delta)
        dv_acc[...] += jax.lax.dot_general(             # P_drop^T @ dO
            pd.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[...] += jax.lax.dot_general(             # dS^T @ Q
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

    if causal:
        needed = ik * block_k <= iq * block_q + block_q - 1 + offset
        pl.when(needed)(_compute)
    else:
        _compute()

    @pl.when((g == group - 1) & (iq == nq - 1))
    def _finalize():
        dk_ref[0, 0] = (dk_acc[...][:, :dk_ref.shape[-1]]
                        .astype(dk_ref.dtype))
        dv_ref[0, 0] = (dv_acc[...][:, :dv_ref.shape[-1]]
                        .astype(dv_ref.dtype))


def _bwd_impl(q, k, v, seed, out, lse, do, causal, sm_scale, dropout_p,
              block_q, block_k, interpret):
    in_dtype = q.dtype
    d_orig = q.shape[-1]
    # delta from the ORIGINAL tensors (padding is exact but pointless
    # here — the row-sum is over real lanes either way)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)             # [B, Hq, Sq, 1]
    mode, dp = _sublane_plan(d_orig, in_dtype, interpret)
    if mode == "fp32":
        q, k, v, do = (x.astype(jnp.float32) for x in (q, k, v, do))
    elif mode == "pad":
        q, k, v, do = (_pad_d(x, dp) for x in (q, k, v, do))
    bsz, hq, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    group = hq // hkv
    bq = _pick_block(sq, block_q, interpret)
    bk = _pick_block(sk, block_k, interpret)
    nq, nk = sq // bq, sk // bk
    offset = sk - sq
    dpad = dp if mode == "kpad" else d

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          dropout_p=dropout_p, offset=offset,
                          block_q=bq, block_k=bk, dpad=dpad),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(bsz, hq, nq, nk),
        in_specs=[
            _SMEM_SPEC,
            pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b, h, i, j, g=group: (b, h // g, j, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b, h, i, j, g=group: (b, h // g, j, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
        scratch_shapes=[_VMEM((bq, dpad), jnp.float32)],
        interpret=interpret,
    )(seed, q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          dropout_p=dropout_p, offset=offset,
                          block_q=bq, block_k=bk, group=group, dpad=dpad),
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        grid=(bsz, hkv, nk, group, nq),
        in_specs=[
            _SMEM_SPEC,
            pl.BlockSpec((1, 1, bq, d),
                         lambda b, hk, j, g, i, G=group: (b, hk * G + g, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b, hk, j, g, i: (b, hk, j, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b, hk, j, g, i: (b, hk, j, 0)),
            pl.BlockSpec((1, 1, bq, d),
                         lambda b, hk, j, g, i, G=group: (b, hk * G + g, i, 0)),
            pl.BlockSpec((1, 1, bq, 1),
                         lambda b, hk, j, g, i, G=group: (b, hk * G + g, i, 0)),
            pl.BlockSpec((1, 1, bq, 1),
                         lambda b, hk, j, g, i, G=group: (b, hk * G + g, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda b, hk, j, g, i: (b, hk, j, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b, hk, j, g, i: (b, hk, j, 0)),
        ],
        scratch_shapes=[_VMEM((bk, dpad), jnp.float32),
                        _VMEM((bk, dpad), jnp.float32)],
        interpret=interpret,
    )(seed, q, k, v, do, lse, delta)
    if mode == "pad":
        dq, dk, dv = (x[..., :d_orig] for x in (dq, dk, dv))
    elif mode == "fp32":
        dq, dk, dv = (x.astype(in_dtype) for x in (dq, dk, dv))
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash(q, k, v, seed, causal, sm_scale, dropout_p, block_q, block_k,
           interpret):
    out, _ = _fwd_impl(q, k, v, seed, causal, sm_scale, dropout_p,
                       block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, seed, causal, sm_scale, dropout_p, block_q, block_k,
               interpret):
    out, lse = _fwd_impl(q, k, v, seed, causal, sm_scale, dropout_p,
                         block_q, block_k, interpret)
    return out, (q, k, v, seed, out, lse)


def _flash_bwd(causal, sm_scale, dropout_p, block_q, block_k, interpret,
               res, do):
    q, k, v, seed, out, lse = res
    dq, dk, dv = _bwd_impl(q, k, v, seed, out, lse, do, causal, sm_scale,
                           dropout_p, block_q, block_k, interpret)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_bhsd(q, k, v, *, causal: bool = False,
                         sm_scale: Optional[float] = None,
                         dropout_p: float = 0.0, seed=None,
                         block_q: Optional[int] = None,
                         block_k: Optional[int] = None,
                         interpret: Optional[bool] = None):
    """Flash attention over ``[B, H, S, D]`` tensors (GQA allowed: K/V may
    have ``Hq / G`` heads). Differentiable; bwd recomputes attention from
    the saved ``[B, H, S]`` fp32 log-sum-exp.

    ``dropout_p`` applies attention-probability dropout inside the kernel,
    seeded by ``seed`` (int32 scalar/array); the same mask is regenerated in
    the backward kernels.
    """
    hq, hkv = q.shape[1], k.shape[1]
    if hq % hkv:
        raise ValueError(f"Hq={hq} not a multiple of Hkv={hkv}")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    it = _interpret() if interpret is None else interpret
    if not supports(q.shape[2], k.shape[2], it):
        raise ValueError(
            f"unsupported seq lens ({q.shape[2]}, {k.shape[2]}) — caller "
            "should fall back to the chunked XLA path")
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    else:
        seed = jnp.asarray(seed, jnp.int32).reshape((1,))
    # sub-lane head dims (D % 128 != 0, bf16, on device) are handled
    # INSIDE _fwd_impl/_bwd_impl (_sublane_plan: zero-pad to a lane
    # multiple by default, keeping native bf16 MXU dots) so the
    # explicit-residual callers (ops/flash_residual.py) get the same
    # treatment as this custom_vjp path.
    if block_q is None or block_k is None:
        # consult the autotune cache (ops/autotune.py); 1024x1024 is the
        # measured default at llama shapes on v5e
        from .autotune import flash_signature, lookup

        tuned = lookup("flash_attention",
                       flash_signature(q.shape[2], k.shape[2], q.shape[-1],
                                       causal, jnp.dtype(q.dtype).name)) \
            or {}
        block_q = block_q or tuned.get("block_q", 1024)
        block_k = block_k or tuned.get("block_k", 1024)
    return _flash(q, k, v, seed, causal, float(sm_scale), float(dropout_p),
                  block_q, block_k, it)
