"""Functional op surface (``paddle.*`` tensor functions).

TPU-native analog of the reference's PHI op library (paddle/phi/kernels/,
python/paddle/tensor/): each op is a thin differentiable wrapper over
jax.numpy/lax — kernel selection, layout transform, and fusion are XLA's job,
so the per-op dispatch machinery (phi/api/lib/kernel_dispatch.h:179)
disappears.
"""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from . import _methods  # noqa: F401  (attaches Tensor methods/dunders)
