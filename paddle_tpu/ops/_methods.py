"""Attach the functional op surface as Tensor methods + arithmetic dunders
(reference: pybind/eager_method.cc:101 tensor methods table)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from . import linalg, logic, manipulation, math as math_ops, search
from ._helpers import nondiff_op


def _binary(fn, name, reflected=False):
    def method(self, other):
        if reflected:
            return apply_op(lambda a, b: fn(b, a), self, other, op_name=name)
        return apply_op(fn, self, other, op_name=name)

    return method


def _cmp(fn, name):
    def method(self, other):
        return nondiff_op(fn, name)(self, other)

    return method


Tensor.__add__ = _binary(jnp.add, "add")
Tensor.__radd__ = _binary(jnp.add, "add", reflected=True)
Tensor.__sub__ = _binary(jnp.subtract, "sub")
Tensor.__rsub__ = _binary(jnp.subtract, "sub", reflected=True)
Tensor.__mul__ = _binary(jnp.multiply, "mul")
Tensor.__rmul__ = _binary(jnp.multiply, "mul", reflected=True)
Tensor.__truediv__ = _binary(jnp.divide, "div")
Tensor.__rtruediv__ = _binary(jnp.divide, "div", reflected=True)
Tensor.__floordiv__ = _binary(jnp.floor_divide, "floordiv")
Tensor.__rfloordiv__ = _binary(jnp.floor_divide, "floordiv", reflected=True)
Tensor.__mod__ = _binary(jnp.mod, "mod")
Tensor.__rmod__ = _binary(jnp.mod, "mod", reflected=True)
Tensor.__pow__ = _binary(jnp.power, "pow")
Tensor.__rpow__ = _binary(jnp.power, "pow", reflected=True)
Tensor.__matmul__ = _binary(jnp.matmul, "matmul")
Tensor.__rmatmul__ = _binary(jnp.matmul, "matmul", reflected=True)
Tensor.__neg__ = lambda self: apply_op(jnp.negative, self, op_name="neg")
Tensor.__abs__ = lambda self: apply_op(jnp.abs, self, op_name="abs")
Tensor.__invert__ = lambda self: nondiff_op(jnp.logical_not, "not")(self)

Tensor.__eq__ = _cmp(jnp.equal, "eq")
Tensor.__ne__ = _cmp(jnp.not_equal, "ne")
Tensor.__lt__ = _cmp(jnp.less, "lt")
Tensor.__le__ = _cmp(jnp.less_equal, "le")
Tensor.__gt__ = _cmp(jnp.greater, "gt")
Tensor.__ge__ = _cmp(jnp.greater_equal, "ge")
Tensor.__and__ = _cmp(jnp.bitwise_and, "and")
Tensor.__or__ = _cmp(jnp.bitwise_or, "or")
Tensor.__xor__ = _cmp(jnp.bitwise_xor, "xor")

# augmented-assign: out-of-place (new value, same python name) like paddle
Tensor.__iadd__ = Tensor.__add__
Tensor.__isub__ = Tensor.__sub__
Tensor.__imul__ = Tensor.__mul__
Tensor.__itruediv__ = Tensor.__truediv__

_METHOD_SOURCES = [
    (
        math_ops,
        "exp log log2 log10 log1p sqrt rsqrt square abs neg sin cos tan asin "
        "acos atan sinh cosh tanh asinh acosh atanh ceil floor round trunc "
        "reciprocal sign erf erfinv sigmoid digamma lgamma frac add subtract "
        "multiply divide floor_divide mod remainder pow maximum minimum fmax "
        "fmin atan2 scale clip lerp sum mean prod max min amax amin nansum "
        "nanmean logsumexp all any count_nonzero std var median quantile "
        "cumsum cumprod cummax cummin logcumsumexp addmm inner outer kron "
        "trace diff nan_to_num increment",
    ),
    (
        manipulation,
        "reshape reshape_ flatten squeeze unsqueeze transpose moveaxis "
        "swapaxes tile expand expand_as broadcast_to flip rot90 roll gather "
        "gather_nd scatter scatter_nd_add index_select index_sample index_add "
        "index_put take_along_axis put_along_axis strided_slice pad unbind "
        "repeat_interleave view view_as unfold masked_fill where numel cast "
        "split chunk unstack",
    ),
    (
        linalg,
        "matmul mm bmm dot mv t norm dist cross cholesky solve inverse det "
        "slogdet matrix_power qr svd pinv eig eigvals multi_dot histogram "
        "bincount",
    ),
    (
        logic,
        "equal not_equal greater_than greater_equal less_than less_equal "
        "equal_all allclose isclose logical_and logical_or logical_xor "
        "logical_not bitwise_and bitwise_or bitwise_xor bitwise_not isnan "
        "isinf isfinite is_empty isin",
    ),
    (
        search,
        "argmax argmin argsort sort topk nonzero masked_select searchsorted "
        "kthvalue mode unique",
    ),
]

for _mod, _names in _METHOD_SOURCES:
    for _n in _names.split():
        if not hasattr(Tensor, _n):
            setattr(Tensor, _n, getattr(_mod, _n))

# property-style helpers
Tensor.T = property(lambda self: linalg.t(self))
Tensor.mT = property(
    lambda self: apply_op(lambda v: jnp.swapaxes(v, -1, -2), self, op_name="mT")
)


# -- in-place variants (reference: inplace_apis_in_dygraph registered per op;
# semantics here follow reshape_: the python object is rebound to the new
# value AND its grad node, so autograd flows through subsequent uses) -------


def _make_inplace(name, fn):
    def method(self, *args, **kwargs):
        out = fn(self, *args, **kwargs)
        self._value = out._value
        self._node = out._node
        self._out_idx = out._out_idx
        self.stop_gradient = out.stop_gradient
        return self

    method.__name__ = name
    return method


_INPLACE_SOURCES = [
    (math_ops, "add subtract multiply ceil clip erfinv exp floor lerp pow "
               "reciprocal remainder round rsqrt scale sigmoid sqrt tanh"),
    (manipulation, "squeeze unsqueeze scatter index_put put_along_axis "
                   "flatten index_fill index_add"),
]

for _mod, _names in _INPLACE_SOURCES:
    for _n in _names.split():
        _iname = _n + "_"
        if not hasattr(Tensor, _iname):
            setattr(Tensor, _iname, _make_inplace(_iname, getattr(_mod, _n)))
