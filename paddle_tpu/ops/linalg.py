"""Linear algebra ops (python/paddle/tensor/linalg.py parity).

matmul maps straight onto the MXU via XLA dot_general — the reference's
blas/cublas wrapper layer (phi/kernels/funcs/blas/) has no analog here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from ._helpers import nondiff_op, unwrap

__all__ = [
    "matmul",
    "mm",
    "bmm",
    "dot",
    "mv",
    "t",
    "einsum",
    "norm",
    "dist",
    "cond",
    "cross",
    "cholesky",
    "cholesky_solve",
    "triangular_solve",
    "lu",
    "qr",
    "svd",
    "pinv",
    "inverse",
    "det",
    "slogdet",
    "matrix_power",
    "matrix_rank",
    "eig",
    "eigh",
    "eigvals",
    "eigvalsh",
    "solve",
    "lstsq",
    "multi_dot",
    "histogram",
    "bincount",
    "corrcoef",
    "cov",
]


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """Reference: legacy_ops.yaml:507 / phi MatmulKernel
    (phi/kernels/impl/matmul_kernel_impl.h:968)."""

    def impl(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return a @ b

    return apply_op(impl, x, y, op_name="matmul")


def mm(input, mat2, name=None):
    return apply_op(jnp.matmul, input, mat2, op_name="mm")


def bmm(x, y, name=None):
    return apply_op(jnp.matmul, x, y, op_name="bmm")


def dot(x, y, name=None):
    return apply_op(
        lambda a, b: jnp.sum(a * b, axis=-1), x, y, op_name="dot"
    )


def mv(x, vec, name=None):
    return apply_op(jnp.matmul, x, vec, op_name="mv")


def t(input, name=None):
    return apply_op(
        lambda v: v.T if v.ndim <= 2 else jnp.swapaxes(v, -1, -2),
        input,
        op_name="t",
    )


def einsum(equation, *operands):
    return apply_op(
        lambda *ops: jnp.einsum(equation, *ops), *operands, op_name="einsum"
    )


def norm(x, p=None, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis

    def impl(v):
        if isinstance(ax, tuple) and len(ax) == 2 and p not in (None, 0):
            # MATRIX norm over the axis pair: induced/Schatten semantics
            # (reference p_matrix_norm — p=±1 column sums, ±inf row sums,
            # 2 spectral, 'fro'/'nuc' Schatten), NOT an elementwise
            # reduction over both axes
            return jnp.linalg.norm(v, ord=p, axis=ax, keepdims=keepdim)
        if p is None or p == "fro":
            if ax is None:
                return jnp.sqrt(jnp.sum(v.astype(jnp.float32) ** 2)).astype(v.dtype)
            return jnp.linalg.norm(v, axis=ax, keepdims=keepdim)
        if p == float("inf"):
            return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(v) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)

    return apply_op(impl, x, op_name="norm")


def dist(x, y, p=2, name=None):
    return norm(apply_op(jnp.subtract, x, y, op_name="sub"), p=p)


def cond(x, p=None, name=None):
    # taped: jnp.linalg.cond is svd/inv-based and differentiable (the
    # r5 check_grad sweep found the bare Tensor wrap dropped grads)
    return apply_op(lambda v: jnp.linalg.cond(v, p=p), x, op_name="cond")


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else None

    def impl(a, b):
        if ax is None:
            for i, d in enumerate(a.shape):
                if d == 3:
                    return jnp.cross(a, b, axis=i)
            raise ValueError("no axis of size 3")
        return jnp.cross(a, b, axis=ax)

    return apply_op(impl, x, y, op_name="cross")


def cholesky(x, upper=False, name=None):
    def impl(v):
        l = jnp.linalg.cholesky(v)
        return jnp.swapaxes(l, -1, -2) if upper else l

    return apply_op(impl, x, op_name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def impl(b, chol):
        c = jnp.swapaxes(chol, -1, -2) if upper else chol
        z = jax.scipy.linalg.solve_triangular(c, b, lower=True)
        return jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(c, -1, -2), z, lower=False
        )

    return apply_op(impl, x, y, op_name="cholesky_solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def impl(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular,
        )

    return apply_op(impl, x, y, op_name="triangular_solve")


def lu(x, pivot=True, get_infos=False, name=None):
    v = unwrap(x)
    lu_, piv = jax.scipy.linalg.lu_factor(v)
    outs = (Tensor(lu_), Tensor(piv + 1))
    if get_infos:
        return outs + (Tensor(jnp.zeros((), jnp.int32)),)
    return outs


def qr(x, mode="reduced", name=None):
    if mode == "complete":
        # JAX has no QR derivative for complete mode — taping would make
        # the FORWARD raise for grad-enabled inputs; keep it untaped
        q, r = jnp.linalg.qr(unwrap(x), mode=mode)
        return Tensor(q), Tensor(r)
    out = apply_op(lambda v: jnp.linalg.qr(v, mode=mode), x, op_name="qr")
    return out if mode == "r" else (out[0], out[1])


def svd(x, full_matrices=False, name=None):
    """Returns (U, S, VH) with U @ diag(S) @ VH == x, matching the reference
    (python/paddle/tensor/linalg.py svd returns VH)."""
    if full_matrices:
        # no JAX SVD derivative for full matrices — untaped (taping would
        # break the forward for grad-enabled inputs)
        u, s, vh = jnp.linalg.svd(unwrap(x), full_matrices=True)
        return Tensor(u), Tensor(s), Tensor(vh)
    out = apply_op(
        lambda v: tuple(jnp.linalg.svd(v, full_matrices=False)),
        x, op_name="svd")
    return out[0], out[1], out[2]


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op(
        lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian),
        x, op_name="pinv")


def inverse(x, name=None):
    return apply_op(jnp.linalg.inv, x, op_name="inverse")


def det(x, name=None):
    return apply_op(jnp.linalg.det, x, op_name="det")


def slogdet(x, name=None):
    def impl(v):
        sign, logdet = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logdet])

    return apply_op(impl, x, op_name="slogdet")


def matrix_power(x, n, name=None):
    return apply_op(
        lambda v: jnp.linalg.matrix_power(v, n), x, op_name="matrix_power"
    )


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return nondiff_op(
        lambda v: jnp.linalg.matrix_rank(v, rtol=tol), "matrix_rank"
    )(x)


def eig(x, name=None):
    w, v = jnp.linalg.eig(unwrap(x))
    return Tensor(w), Tensor(v)


def eigh(x, UPLO="L", name=None):
    out = apply_op(lambda v: tuple(jnp.linalg.eigh(v, UPLO=UPLO)), x,
                   op_name="eigh")
    return out[0], out[1]


def eigvals(x, name=None):
    return Tensor(jnp.linalg.eigvals(unwrap(x)))


def eigvalsh(x, UPLO="L", name=None):
    return apply_op(
        lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO), x, op_name="eigvalsh"
    )


def solve(x, y, name=None):
    return apply_op(jnp.linalg.solve, x, y, op_name="solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = jnp.linalg.lstsq(unwrap(x), unwrap(y), rcond=rcond)
    return Tensor(sol), Tensor(res), Tensor(rank), Tensor(sv)


def multi_dot(tensors, name=None):
    return apply_op(
        lambda *vs: jnp.linalg.multi_dot(vs), *tensors, op_name="multi_dot"
    )


def histogram(input, bins=100, min=0, max=0, name=None):
    def impl(v):
        lo, hi = (min, max) if (min != 0 or max != 0) else (v.min(), v.max())
        h, _ = jnp.histogram(v, bins=bins, range=(lo, hi))
        return h

    return nondiff_op(impl, "histogram")(input)


def bincount(x, weights=None, minlength=0, name=None):
    v = unwrap(x)
    w = unwrap(weights)
    length = builtins_max(int(v.max()) + 1 if v.size else 0, minlength)
    return Tensor(jnp.bincount(v, weights=w, length=length))


builtins_max = max


def corrcoef(x, rowvar=True, name=None):
    return apply_op(lambda v: jnp.corrcoef(v, rowvar=rowvar), x,
                    op_name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply_op(
        lambda v: jnp.cov(
            v, rowvar=rowvar, ddof=1 if ddof else 0,
            fweights=unwrap(fweights), aweights=unwrap(aweights),
        ),
        x,
        op_name="cov",
    )


# ---- round-2 long tail -----------------------------------------------------


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Pairwise p-distance between row batches (linalg.py cdist)."""
    def f(a, b):
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            # exact 0 for identical rows; grad-safe via the where trick
            # (sqrt'(0) = inf would poison the vjp otherwise)
            d2 = jnp.sum(d * d, -1)
            return jnp.where(d2 == 0, 0.0,
                             jnp.sqrt(jnp.where(d2 == 0, 1.0, d2)))
        if p == float("inf"):
            return jnp.max(jnp.abs(d), -1)
        if p == 0:
            return jnp.sum((d != 0).astype(a.dtype), -1)
        return jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)

    return apply_op(f, x, y, op_name="cdist")


def tensordot(x, y, axes=2, name=None):
    def norm_axes(ax):
        if isinstance(ax, Tensor):
            ax = ax.tolist()
        return ax

    return apply_op(lambda a, b: jnp.tensordot(a, b, axes=norm_axes(axes)),
                    x, y, op_name="tensordot")


def inv(x, name=None):
    """paddle.linalg.inv alias of inverse."""
    return inverse(x, name=name)


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack lu() results into P, L, U (linalg.py lu_unpack)."""
    lu_v = unwrap(x)
    piv = unwrap(y)
    m, n = lu_v.shape[-2], lu_v.shape[-1]
    k = min(m, n)

    def f(lu_a):
        l = jnp.tril(lu_a[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_a.dtype)
        u = jnp.triu(lu_a[..., :k, :])
        return l, u

    def perm(piv_a):
        # pivots (1-based row swaps) → permutation matrix
        def one(pv):
            perm_idx = jnp.arange(m)

            def body(i, pi):
                j = pv[i] - 1
                a, b = pi[i], pi[j]
                return pi.at[i].set(b).at[j].set(a)

            pi = jax.lax.fori_loop(0, pv.shape[0], body, perm_idx)
            return jnp.eye(m, dtype=lu_v.dtype)[pi].T

        flat = piv_a.reshape((-1, piv_a.shape[-1]))
        mats = jax.vmap(one)(flat)
        return mats.reshape(piv_a.shape[:-1] + (m, m))

    p_t = Tensor(perm(piv)) if unpack_pivots else None
    if unpack_ludata:
        l_t, u_t = apply_op(f, x, op_name="lu_unpack")
    else:
        l_t = u_t = None
    return p_t, l_t, u_t


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA (linalg.py pca_lowrank): returns (U, S, V)."""
    v = unwrap(x)
    m, n = v.shape[-2], v.shape[-1]
    q_ = q if q is not None else min(6, m, n)

    def f(a):
        if center:
            a = a - jnp.mean(a, axis=-2, keepdims=True)
        key = jax.random.PRNGKey(0)
        omega = jax.random.normal(key, a.shape[:-2] + (n, q_), a.dtype)
        y = a @ omega
        for _ in range(niter):
            y = a @ (a.swapaxes(-1, -2) @ y)
        qmat, _ = jnp.linalg.qr(y)
        b = qmat.swapaxes(-1, -2) @ a
        u_b, s, vt = jnp.linalg.svd(b, full_matrices=False)
        return qmat @ u_b, s, vt.swapaxes(-1, -2)

    from ._helpers import nondiff_op as _nd

    return _nd(f, "pca_lowrank")(x)


for _n in ("cdist", "tensordot", "inv", "lu_unpack", "pca_lowrank"):
    __all__.append(_n)


def householder_product(x, tau, name=None):
    """Assemble Q from Householder reflectors (reference
    tensor/linalg.py householder_product / LAPACK orgqr): columns of x
    hold the reflector vectors v_i (unit lower-triangular part), tau the
    coefficients; Q = H_1 H_2 ... H_k restricted to the first k columns."""
    from ._helpers import nondiff_op as _nd

    def f(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        eye = jnp.broadcast_to(eye, a.shape[:-2] + (m, m))

        def body(q, i):
            v = a[..., :, i]
            # reflector vector: v[j<i] = 0, v[i] = 1, v[j>i] from x
            idx = jnp.arange(m)
            v = jnp.where(idx < i, 0.0, v)
            v = jnp.where(idx == i, 1.0, v)
            h = (t[..., i][..., None, None]
                 * v[..., :, None] * v[..., None, :])
            return q - q @ h.astype(q.dtype), None

        q, _ = jax.lax.scan(body, eye, jnp.arange(n))
        return q[..., :, :n]

    return _nd(f, "householder_product")(x, tau)


__all__.append("householder_product")
